"""Elastic fleet autoscaling + live KV session migration (ISSUE 19):
the ``AutoscalePolicy`` control loop (hysteresis, cooldown, ANY-up /
ALL-down trigger logic, fleet bounds, the disaggregated prefill:decode
retune), the loadgen shaped-load profiles, and the cluster chaos
suite — scale-down drains that live-migrate every resident session
TOKEN-EXACT vs never-migrated (fp, int8 KV, n-gram speculation, and a
resident LoRA adapter), scale-up under burst admitting the queued
backlog, a target replica dying mid-migration (aborts cleanly, the
session re-seats elsewhere), the payload-loss recompute degrade, zero
steady-state recompiles across a scale cycle, the
``PADDLE_TPU_AUTOSCALE=0`` kill switch (bit-parity with a fixed-N
fleet), the fail_replica published-prefix purge regression, cancel of
an in-transit migration, and priority-aware cluster rebalancing.

Tier-1 guard: every test here must run in the standard
``-m 'not slow'`` sweep — ``test_tier1_no_slow_marker`` pins that.
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ServingConfig, ServingEngine
from paddle_tpu.inference.autoscale import (AutoscaleConfig,
                                            AutoscalePolicy)
from paddle_tpu.inference.cluster import ClusterConfig, EngineCluster
from paddle_tpu.inference.loadgen import (profile_arrivals, run_load,
                                          _profile_rate)
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.ops import paged_cache as _pc


@pytest.fixture
def llama_tiny():
    paddle.seed(7)
    cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                           kv_heads=2, ffn=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _scfg(**kw):
    base = dict(num_slots=2, block_size=8, max_model_len=96,
                prefill_chunk=8, min_prefill_bucket=8)
    base.update(kw)
    return ServingConfig(**base)


def _prompts(rng, lens=(11, 19, 9, 14), vocab=128):
    return [rng.randint(1, vocab, (n,)) for n in lens]


def _lora_w(seed, rank=4, d=64, names=("q_proj", "o_proj")):
    # q/o only: k/v project to the GQA width on this fixture
    rng = np.random.RandomState(seed)
    return {n: (rng.normal(0, 0.3, (d, rank)).astype(np.float32),
                rng.normal(0, 0.3, (rank, d)).astype(np.float32))
            for n in names}


# ------------------------------------------------------ policy (unit)


def _sig(replicas=2, slots=4, active=0, queued=0, burn=0.0, busy=0.0):
    return {"replicas": replicas, "slots": slots, "active": active,
            "queued": queued, "burn_fast": burn, "busy": busy}


def test_policy_hysteresis_then_cooldown():
    """A breach must hold ``hysteresis_ticks`` CONSECUTIVE ticks to
    act, any action opens a ``cooldown_ticks`` hold-down, and one
    clean tick resets the streak."""
    pol = AutoscalePolicy(AutoscaleConfig(
        max_replicas=4, hysteresis_ticks=3, cooldown_ticks=5))
    hot = _sig(queued=8)                    # 2 queued/slot >= 0.5
    assert pol.decide(hot) == "hold"
    assert pol.decide(hot) == "hold"
    assert pol.decide(hot) == "up"          # 3rd consecutive breach
    for _ in range(5):                      # cooldown absorbs breaches
        assert pol.decide(hot) == "hold"
    # the streak accumulated THROUGH the cooldown: a pressure that
    # outlives the hold-down acts the very next tick
    assert pol.decide(hot) == "up"
    # a single clean tick resets the streak
    pol2 = AutoscalePolicy(AutoscaleConfig(hysteresis_ticks=3,
                                           cooldown_ticks=0))
    pol2.decide(hot), pol2.decide(hot)
    assert pol2.decide(_sig()) == "hold"    # breach streak broken
    assert pol2.decide(hot) == "hold"
    assert pol2.decide(hot) == "hold"
    assert pol2.decide(hot) == "up"
    st = pol2.state()
    assert st["decisions"]["up"] == 1 and st["cooldown_remaining"] == 0


def test_policy_any_up_all_down_and_bounds():
    """ANY up-trigger scales up (queue, occupancy, SLO burn, roofline
    busy each fire alone); scale-down needs occupancy AND queue BOTH
    under their floors; the fleet never leaves [min, max]."""
    mk = lambda: AutoscalePolicy(AutoscaleConfig(
        min_replicas=1, max_replicas=4, hysteresis_ticks=1,
        cooldown_ticks=0))
    for kw in (dict(queued=8), dict(active=4), dict(burn=20.0),
               dict(busy=0.99)):
        assert mk().decide(_sig(**kw)) == "up", kw
    # down: occupancy floor alone is NOT enough when the queue holds
    pol = mk()
    assert pol.decide(_sig(active=0, queued=1)) == "hold"
    assert pol.decide(_sig(active=0, queued=0)) == "down"
    # bounds clamp both directions even with the trigger held
    assert mk().decide(_sig(replicas=4, queued=40)) == "hold"
    assert mk().decide(_sig(replicas=1, active=0, queued=0)) == "hold"


def test_policy_prefill_retune_and_validation():
    """``decide_prefill`` retunes the prefill:decode ratio from the
    prefill tier's queue-per-slot (the prompt-length-mix pressure
    signal), shares the action cooldown, and bad configs raise."""
    pol = AutoscalePolicy(AutoscaleConfig(
        hysteresis_ticks=2, cooldown_ticks=0,
        min_prefill_replicas=1, max_prefill_replicas=3))
    psig = {"prefill_replicas": 1, "prefill_slots": 2,
            "prefill_active": 0, "prefill_queued": 4}
    assert pol.decide_prefill(psig) == "hold"
    assert pol.decide_prefill(psig) == "up"
    idle = {"prefill_replicas": 2, "prefill_slots": 4,
            "prefill_active": 0, "prefill_queued": 0}
    assert pol.decide_prefill(idle) == "hold"
    assert pol.decide_prefill(idle) == "down"
    assert pol.state()["decisions"]["prefill_up"] == 1
    # bounds: a 0-max config never touches the prefill tier
    off = AutoscalePolicy(AutoscaleConfig(hysteresis_ticks=1))
    assert off.decide_prefill(psig) == "hold"
    for bad in (dict(min_replicas=0), dict(max_replicas=0),
                dict(min_prefill_replicas=2, max_prefill_replicas=1),
                dict(hysteresis_ticks=0), dict(cooldown_ticks=-1)):
        with pytest.raises(ValueError):
            AutoscaleConfig(**bad)


# -------------------------------------------------- loadgen profiles


def test_profile_arrivals_seeded_and_shaped():
    """Shaped arrival offsets are monotone, reproducible per seed,
    and actually shaped: a ramp's early gaps dwarf its late gaps, a
    step's first half-period packs more arrivals than its second."""
    prof = {"kind": "ramp", "ramp_s": 30.0, "start_frac": 0.05}
    a = profile_arrivals(64, 4.0, prof, seed=3)
    b = profile_arrivals(64, 4.0, prof, seed=3)
    assert np.array_equal(a, b) and a.shape == (64,)
    assert np.all(np.diff(a) >= 0)
    assert not np.array_equal(a, profile_arrivals(64, 4.0, prof,
                                                  seed=4))
    gaps = np.diff(a)
    assert gaps[:16].mean() > 2.0 * gaps[-16:].mean()
    step = {"kind": "step", "period_s": 10.0, "high": 4.0,
            "low": 0.25}
    s = profile_arrivals(200, 2.0, step, seed=0)
    in_burst = ((s % 10.0) < 5.0).mean()
    assert in_burst > 0.7                   # bursts absorb most mass
    # λ(t) itself: sine peaks mid-period, floors at 5% of base
    sine = {"kind": "sine", "period_s": 4.0, "depth": 1.0}
    assert _profile_rate(sine, 2.0, 1.0) == pytest.approx(4.0)
    assert _profile_rate(sine, 2.0, 3.0) == pytest.approx(0.1)
    with pytest.raises(ValueError):
        _profile_rate({"kind": "sawtooth"}, 1.0, 0.0)


def test_loadgen_profile_rows_report_and_guards(llama_tiny, tmp_path):
    """``run_load(qps_profile=...)`` echoes the profile in the report
    and on EVERY NDJSON row; without a profile the rows carry no
    ``qps_profile`` key (byte-identical to the fixed-QPS format); a
    closed loop rejects the knob outright."""
    rng = np.random.RandomState(5)
    eng = ServingEngine(llama_tiny, _scfg())
    with pytest.raises(ValueError):
        run_load(eng, _prompts(rng, lens=(7, 9)), mode="closed",
                 concurrency=2, qps=4.0,
                 qps_profile={"kind": "sine"})
    prof = {"kind": "step", "period_s": 0.4, "high": 3.0, "low": 0.5}
    p1 = tmp_path / "shaped.ndjson"
    rep = run_load(eng, _prompts(rng, lens=(7, 9, 11)), qps=40.0,
                   max_new_tokens=3, qps_profile=prof,
                   record_path=str(p1), seed=1)
    assert rep["qps_profile"] == prof
    rows = [json.loads(ln) for ln in p1.read_text().splitlines()]
    assert len(rows) == 3
    assert all(r["qps_profile"] == prof for r in rows)
    p2 = tmp_path / "fixed.ndjson"
    rep2 = run_load(eng, _prompts(rng, lens=(7, 9)), qps=40.0,
                    max_new_tokens=3, record_path=str(p2), seed=1)
    assert "qps_profile" not in rep2
    assert all("qps_profile" not in json.loads(ln)
               for ln in p2.read_text().splitlines())
    eng.shutdown()


# -------------------------------------- live migration: token-exact


def _drain_mid_decode(cl, rids, max_new):
    """Tick until at least one request has streamed a token but none
    finished, then drain the coldest replica."""
    for _ in range(24):
        cl.step()
        toks = [len(cl._tokens[r]) for r in rids]
        if max(toks) >= 1 and max(toks) < max_new:
            break
    return cl.scale_down()


@pytest.mark.parametrize("variant", ["fp", "int8", "spec", "lora"])
def test_scale_down_drain_token_exact(llama_tiny, variant):
    """THE migration bar: a scale-down drain live-migrates every
    resident session and greedy output stays token-exact vs a
    never-migrated single engine — for fp KV, int8 KV (payload = data
    + per-row scales), n-gram speculation (the drafter corpus rebuilds
    from the migrated history), and a resident LoRA adapter (the pin
    re-acquires on the target)."""
    kw = {"int8": dict(kv_cache_dtype="int8"),
          "spec": dict(num_speculative_tokens=2),
          "lora": dict(lora_rank=4, max_adapters=4)}.get(variant, {})
    rng = np.random.RandomState(13)
    prompts = _prompts(rng)
    max_new = 8
    sub = dict(adapter_id=1) if variant == "lora" else {}

    eng = ServingEngine(llama_tiny, _scfg(**kw))
    if variant == "lora":
        eng.load_adapter(1, _lora_w(101))
    refs = []
    for p in prompts:
        rid = eng.submit(p.copy(), max_new, **sub)
        done = eng.run()
        refs.append(done[rid].tolist())
    eng.shutdown()

    cl = EngineCluster(llama_tiny, ClusterConfig(num_replicas=2),
                       _scfg(**kw))
    if variant == "lora":
        cl.load_adapter(1, _lora_w(101))
    rids = [cl.submit(p.copy(), max_new, **sub) for p in prompts]
    dropped = _drain_mid_decode(cl, rids, max_new)
    done = cl.run()
    for rid, ref in zip(rids, refs):
        assert done[rid].tolist() == ref, variant
    st = cl.stats()
    assert st["sessions_migrated"] >= 1
    assert st["scale_downs"] == 1 and st["replicas_live"] == 1
    assert dropped in st["removed_replicas"]
    assert st["migration_ms"]["count"] == st["sessions_migrated"]
    # the drained replica's affinity surface is gone
    assert cl.engines[dropped].published_overlap(
        list(_pc.prompt_block_hashes(cl._router._fp, prompts[0],
                                     cl._router._bs))) == 0
    cl.shutdown()


def test_scale_up_under_burst_admits_backlog(llama_tiny):
    """The automatic loop end-to-end: a queue burst trips the policy
    after its hysteresis, the fleet grows to max_replicas, and the
    EXISTING backlog spreads onto the new replica (``shed_queued`` →
    router) — the burst drains through both replicas, every request
    completes in full, and the new replica provably served some."""
    burst = AutoscaleConfig(min_replicas=1, max_replicas=2,
                            up_queue_per_slot=0.5,
                            hysteresis_ticks=2, cooldown_ticks=64)
    cl = EngineCluster(llama_tiny,
                       ClusterConfig(num_replicas=1, autoscale=burst),
                       _scfg())
    rng = np.random.RandomState(3)
    rids = [cl.submit(rng.randint(1, 128, (9,)), 4)
            for _ in range(8)]
    done = cl.run()
    assert set(done) == set(rids)
    assert all(len(done[r]) == 4 for r in rids)
    st = cl.stats()
    assert st["scale_ups"] == 1 and st["replicas_live"] == 2
    assert st["autoscale"]["decisions"]["up"] == 1
    assert st["replicas"][1]["requests_completed"] > 0
    cl.shutdown()


def test_kill_during_migration_fails_target_resumes_elsewhere(
        llama_tiny):
    """Chaos: the COLDEST survivor dies while admitting a migrated
    session. The cluster fails it mid-migration, re-derives the live
    set, and the session seats on the next candidate — still
    token-exact; the poisoned replica lands in failed_replicas."""
    rng = np.random.RandomState(17)
    prompts = _prompts(rng, lens=(11, 19))
    max_new = 8
    eng = ServingEngine(llama_tiny, _scfg())
    refs = [eng.serve([p.copy()], max_new)[0].tolist()
            for p in prompts]
    eng.shutdown()

    cl = EngineCluster(llama_tiny, ClusterConfig(num_replicas=3),
                       _scfg())
    rids = [cl.submit(p.copy(), max_new) for p in prompts]
    for _ in range(24):
        cl.step()
        if all(len(cl._tokens[r]) >= 1 for r in rids):
            break
    src = cl._owner[rids[0]][0]
    # the empty replica is the coldest: it will be tried first — and
    # it dies on admission
    busy = {cl._owner[r][0] for r in rids}
    (idle,) = set(cl._decode_idx) - busy

    def _boom(rec):
        raise RuntimeError("injected: replica died mid-import")

    cl.engines[idle].admit_migrated = _boom
    cl.scale_down(src)
    st = cl.stats()
    assert idle in st["failed_replicas"]
    assert st["sessions_migrated"] >= 1     # re-seated on survivor
    done = cl.run()
    for rid, ref in zip(rids, refs):
        assert done[rid].tolist() == ref
    cl.shutdown()


def test_migration_payload_loss_degrades_to_recompute(llama_tiny):
    """A migration whose KV payload is lost (the kill-mid-transfer
    shape) degrades to the recompute path: the target re-prefills the
    context and restores the continuation — still token-exact."""
    rng = np.random.RandomState(19)
    prompts = _prompts(rng, lens=(11, 19))
    max_new = 8
    eng = ServingEngine(llama_tiny, _scfg())
    refs = [eng.serve([p.copy()], max_new)[0].tolist()
            for p in prompts]
    eng.shutdown()

    cl = EngineCluster(llama_tiny, ClusterConfig(num_replicas=2),
                       _scfg())
    rids = [cl.submit(p.copy(), max_new) for p in prompts]
    for _ in range(24):
        cl.step()
        if all(len(cl._tokens[r]) >= 1 for r in rids):
            break
    src = cl._owner[rids[0]][0]
    hot = cl.engines[src]
    orig = hot.export_session

    def _lossy(i):
        rec = orig(i)
        rec.payload = None                  # the bytes died in flight
        return rec

    hot.export_session = _lossy
    cl.scale_down(src)
    done = cl.run()
    for rid, ref in zip(rids, refs):
        assert done[rid].tolist() == ref
    cl.shutdown()


def test_zero_recompiles_across_scale_cycle(llama_tiny):
    """Steady-state elasticity compiles NOTHING: after one full
    drain → migrate → revive cycle (which builds the fixed-width
    export/import pair once), a second identical cycle leaves every
    replica's ``executables_compiled`` exactly where it was."""
    rng = np.random.RandomState(23)
    cl = EngineCluster(llama_tiny, ClusterConfig(num_replicas=2),
                       _scfg())
    cl.serve(_prompts(rng), max_new_tokens=5)           # warm wave

    def _cycle():
        rids = [cl.submit(p.copy(), 8)
                for p in _prompts(rng, lens=(11, 19))]
        for _ in range(24):
            cl.step()
            if all(len(cl._tokens[r]) >= 1 for r in rids):
                break
        idx = cl.scale_down(1)
        cl.run()
        assert cl.scale_up() == idx                     # revived
        return idx

    _cycle()                                # builds the migration pair
    execs0 = [e.stats()["executables_compiled"] for e in cl.engines]
    _cycle()
    execs1 = [e.stats()["executables_compiled"] for e in cl.engines]
    assert execs1 == execs0, (execs0, execs1)
    st = cl.stats()
    assert st["scale_downs"] == 2 and st["scale_ups"] == 2
    assert st["replicas_live"] == 2 and not st["removed_replicas"]
    cl.shutdown()


def test_autoscale_kill_switch_bit_parity(llama_tiny, monkeypatch):
    """PADDLE_TPU_AUTOSCALE=0 beats an explicit (and aggressive)
    policy config: the cluster runs as a fixed-N fleet, never scales,
    and its outputs are bit-identical to one configured without a
    policy."""
    rng = np.random.RandomState(29)
    prompts = _prompts(rng)
    cl = EngineCluster(llama_tiny, ClusterConfig(num_replicas=2),
                       _scfg())
    ref = cl.serve([p.copy() for p in prompts], max_new_tokens=5)
    cl.shutdown()
    monkeypatch.setenv("PADDLE_TPU_AUTOSCALE", "0")
    hair = AutoscaleConfig(min_replicas=1, max_replicas=4,
                           up_queue_per_slot=0.01, down_occupancy=0.9,
                           down_queue_per_slot=0.9,
                           hysteresis_ticks=1, cooldown_ticks=0)
    cl2 = EngineCluster(llama_tiny,
                        ClusterConfig(num_replicas=2, autoscale=hair),
                        _scfg())
    out = cl2.serve([p.copy() for p in prompts], max_new_tokens=5)
    for a, b in zip(out, ref):
        assert a.tolist() == b.tolist()
    st = cl2.stats()
    assert st["autoscale"] is None
    assert st["scale_ups"] == 0 and st["scale_downs"] == 0
    assert st["replicas_live"] == 2
    cl2.shutdown()


# ----------------------------------------- router/affinity hygiene


def test_fail_replica_purges_published_prefixes(llama_tiny):
    """Regression (ISSUE 19 satellite): killing a replica wipes its
    published-prefix surface — ``published_overlap`` scores 0 on the
    corpse — and a session's turn 2 routes to a survivor and
    completes."""
    rng = np.random.RandomState(31)
    turn1 = rng.randint(1, 128, (24,))          # 3 full blocks
    turn2 = np.concatenate([turn1, rng.randint(1, 128, (8,))])
    cl = EngineCluster(llama_tiny, ClusterConfig(num_replicas=2),
                       _scfg())
    r1 = cl.submit(turn1.copy(), 4)
    owner = cl._owner[r1][0]
    cl.run()
    hashes = list(_pc.prompt_block_hashes(cl._router._fp, turn1,
                                         cl._router._bs))
    assert cl.engines[owner].published_overlap(hashes) >= 1
    cl.fail_replica(owner)
    assert cl.engines[owner].published_overlap(hashes) == 0
    r2 = cl.submit(turn2.copy(), 4)
    assert cl._owner[r2][0] != owner
    done = cl.run()
    assert len(done[r2]) == 4
    cl.shutdown()


def test_cancel_in_transit_migration(llama_tiny):
    """A migrated session parked between replicas (every candidate
    says "not right now") is still cancellable: the record drops, the
    request terminates with the tokens already streamed, and the rest
    of the drain completes."""
    rng = np.random.RandomState(37)
    cl = EngineCluster(llama_tiny, ClusterConfig(num_replicas=2),
                       _scfg())
    rids = [cl.submit(p.copy(), 8)
            for p in _prompts(rng, lens=(11, 19))]
    for _ in range(24):
        cl.step()
        if all(len(cl._tokens[r]) >= 1 for r in rids):
            break
    src = cl._owner[rids[0]][0]
    (dst,) = set(cl._decode_idx) - {src}
    surv = cl.engines[dst]
    orig = surv.admit_migrated
    surv.admit_migrated = lambda rec: None      # "no capacity" forever
    cl.scale_down(src)
    st = cl.stats()
    assert st["pending_migrations"] >= 1
    parked = [g for g, _ in cl._pending_mig]
    victim = parked[0]
    assert cl.cancel(victim) is True
    assert victim not in [g for g, _ in cl._pending_mig]
    surv.admit_migrated = orig                  # capacity returns
    done = cl.run()
    assert set(done) == set(rids)
    survivors = [r for r in rids if r != victim]
    assert all(len(done[r]) == 8 for r in survivors)
    assert len(done[victim]) < 8                # streamed-so-far only
    cl.shutdown()


def test_rebalance_sheds_lowest_priority_to_coldest(llama_tiny):
    """Cluster rebalancing: when one replica runs >= 2 sessions
    deeper than the coldest, the hot replica's LOWEST-priority
    session live-migrates over — and both streams stay token-exact."""
    rng = np.random.RandomState(41)
    pa, pb = _prompts(rng, lens=(11, 19))
    max_new = 10
    eng = ServingEngine(llama_tiny, _scfg())
    ref_a = eng.serve([pa.copy()], max_new)[0].tolist()
    ref_b = eng.serve([pb.copy()], max_new)[0].tolist()
    eng.shutdown()

    cl = EngineCluster(llama_tiny, ClusterConfig(num_replicas=1),
                       _scfg())
    ra = cl.submit(pa.copy(), max_new, priority=5)
    rb = cl.submit(pb.copy(), max_new)          # priority 0: victim
    for _ in range(24):
        cl.step()
        if all(len(cl._tokens[r]) >= 1 for r in (ra, rb)):
            break
    new = cl.scale_up()                         # cold and empty
    assert cl.rebalance() == 1
    assert cl._owner[rb][0] == new              # lowest priority moved
    assert cl._owner[ra][0] == 0                # high-priority stayed
    done = cl.run()
    assert done[ra].tolist() == ref_a
    assert done[rb].tolist() == ref_b
    assert cl.stats()["sessions_migrated"] == 1
    cl.shutdown()


def test_scale_guards_and_stats_surface(llama_tiny):
    """API guards (can't drain the last decode replica, bad indices
    and roles raise) and the always-present elastic stats surface on
    a plain fixed-N cluster."""
    cl = EngineCluster(llama_tiny, ClusterConfig(num_replicas=1),
                       _scfg())
    with pytest.raises(RuntimeError):
        cl.scale_down()
    with pytest.raises(ValueError):
        cl.scale_down(7)
    with pytest.raises(ValueError):
        cl.scale_up(role="gpu")
    with pytest.raises(ValueError):
        cl.scale_up(role="prefill")     # colocated: no prefill tier
    st = cl.stats()
    for k in ("replicas_live", "removed_replicas", "scale_ups",
              "scale_downs", "sessions_migrated",
              "pending_migrations", "migration_ms", "replica_ticks",
              "mean_prompt_len", "autoscale"):
        assert k in st, k
    assert st["replicas_live"] == 1 and st["autoscale"] is None
    assert st["migration_ms"]["count"] == 0
    assert st["removed_replicas"] == []
    cl.step()
    assert cl.stats()["replica_ticks"] == 1
    cl.shutdown()


def test_tier1_no_slow_marker():
    """CI guard (the PR-4/5 pattern): every autoscale test runs in
    the tier-1 ``-m 'not slow'`` sweep, the token-exact drain matrix
    is present, and every cluster/engine tears down through the
    leak-sweeping ``shutdown()``."""
    import tests.conftest as c
    here = open(__file__).read()
    assert "pytest.mark.slow" not in here.replace(
        '"pytest.mark.slow"', "")
    names = [ln.split("(")[0][4:] for ln in here.splitlines()
             if ln.startswith("def test_")]
    overlap = set(names) & set(c._SLOW_TESTS)
    assert not overlap, f"tier-1 autoscale tests marked slow: {overlap}"
    assert "test_scale_down_drain_token_exact" in names
    assert "test_zero_recompiles_across_scale_cycle" in names
    assert here.count(".shutdown()") >= 12, \
        "cluster shutdown (leak sweep) must guard these tests"
