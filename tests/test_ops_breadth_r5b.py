"""Second round-5 breadth batch: matrix_exp, cholesky_inverse,
svd_lowrank, roi_pool, softmax_mask_fuse, cartesian_prod, vmap,
embedding_bag (references: ``paddle.linalg``, ``paddle.vision.ops``,
``paddle.incubate``, ``paddle.nn.functional``)."""
import numpy as np
import pytest
from scipy import linalg as sla

import paddle_tpu as paddle


def test_matrix_exp():
    a = np.random.RandomState(0).randn(4, 4).astype(np.float32) * 0.3
    out = paddle.linalg.matrix_exp(paddle.to_tensor(a))
    np.testing.assert_allclose(out.numpy(), sla.expm(a), rtol=1e-4,
                               atol=1e-5)


def test_cholesky_inverse():
    rng = np.random.RandomState(1)
    m = rng.randn(5, 5).astype(np.float32)
    a = m @ m.T + 5 * np.eye(5, dtype=np.float32)
    l = np.linalg.cholesky(a)
    out = paddle.linalg.cholesky_inverse(paddle.to_tensor(l))
    np.testing.assert_allclose(out.numpy(), np.linalg.inv(a),
                               rtol=1e-3, atol=1e-4)


def test_svd_lowrank():
    rng = np.random.RandomState(2)
    # a genuinely low-rank matrix: rank 3
    a = (rng.randn(20, 3) @ rng.randn(3, 12)).astype(np.float32)
    u, s, v = paddle.linalg.svd_lowrank(paddle.to_tensor(a), q=5)
    approx = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
    np.testing.assert_allclose(approx, a, rtol=1e-3, atol=1e-3)
    s_full = np.linalg.svd(a, compute_uv=False)
    np.testing.assert_allclose(s.numpy()[:3], s_full[:3], rtol=1e-3)


def test_roi_pool():
    x = np.arange(2 * 1 * 8 * 8, dtype=np.float32).reshape(2, 1, 8, 8)
    boxes = np.array([[0, 0, 4, 4], [2, 2, 8, 8]], np.float32)
    nums = np.array([1, 1], np.int32)
    out = paddle.vision.ops.roi_pool(
        paddle.to_tensor(x), paddle.to_tensor(boxes),
        paddle.to_tensor(nums), output_size=2)
    assert tuple(out.shape) == (2, 1, 2, 2)
    # roi 0 on image 0: windows [0:2,0:2],[0:2,2:4],[2:4,0:2],[2:4,2:4]
    np.testing.assert_allclose(out.numpy()[0, 0],
                               [[9., 11.], [25., 27.]])
    # roi 1 on image 1 (feature base 64): window maxes of [2:8] quads
    ref = x[1, 0]
    np.testing.assert_allclose(
        out.numpy()[1, 0],
        [[ref[2:5, 2:5].max(), ref[2:5, 5:8].max()],
         [ref[5:8, 2:5].max(), ref[5:8, 5:8].max()]])


def test_softmax_mask_fuse():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 4, 8, 8).astype(np.float32)
    mask = np.where(rng.rand(2, 1, 8, 8) > 0.5, 0.0, -1e9) \
        .astype(np.float32)
    out = paddle.incubate.softmax_mask_fuse(
        paddle.to_tensor(x), paddle.to_tensor(mask))
    ref = np.exp(x + mask - (x + mask).max(-1, keepdims=True))
    ref = ref / ref.sum(-1, keepdims=True)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-6)
    ut = paddle.incubate.softmax_mask_fuse_upper_triangle(
        paddle.to_tensor(x))
    arr = ut.numpy()
    assert np.allclose(arr[..., 0, 1:], 0.0)   # causal row 0


def test_cartesian_prod():
    a = paddle.to_tensor(np.array([1, 2], np.int64))
    b = paddle.to_tensor(np.array([3, 4, 5], np.int64))
    out = paddle.cartesian_prod([a, b])
    assert tuple(out.shape) == (6, 2)
    np.testing.assert_array_equal(
        out.numpy(), [[1, 3], [1, 4], [1, 5], [2, 3], [2, 4], [2, 5]])


def test_incubate_vmap():
    def f(x):
        return (x * 2.0).sum()

    batched = paddle.incubate.autograd.vmap(f)
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(3, 2))
    out = batched(x)
    np.testing.assert_allclose(out.numpy(), [2., 10., 18.])


def test_embedding_bag_2d_and_offsets():
    w = paddle.to_tensor(
        np.arange(20, dtype=np.float32).reshape(10, 2))
    ids2 = paddle.to_tensor(np.array([[0, 1], [2, 3]], np.int64))
    out = paddle.nn.functional.embedding_bag(ids2, w, mode="mean")
    np.testing.assert_allclose(out.numpy(), [[1., 2.], [5., 6.]])
    out_sum = paddle.nn.functional.embedding_bag(ids2, w, mode="sum")
    np.testing.assert_allclose(out_sum.numpy(), [[2., 4.], [10., 12.]])
    # 1-D + offsets: bags [0,1,2] and [3]
    ids1 = paddle.to_tensor(np.array([0, 1, 2, 3], np.int64))
    offs = paddle.to_tensor(np.array([0, 3], np.int64))
    out1 = paddle.nn.functional.embedding_bag(ids1, w, offsets=offs,
                                              mode="sum")
    np.testing.assert_allclose(out1.numpy(), [[6., 9.], [6., 7.]])
    outm = paddle.nn.functional.embedding_bag(ids1, w, offsets=offs,
                                              mode="max")
    np.testing.assert_allclose(outm.numpy(), [[4., 5.], [6., 7.]])
