"""Tier-1-safe telemetry smoke (ISSUE 2 CI satellite): run the
multichip dryrun's MoE EP train-step config — the dryrun building block
with explicit shard_map collectives — with metrics export ON, and
assert the JSONL parses and contains the collective-census keys."""
import importlib.util
import json
import os

import numpy as np
import pytest

import jax


def _load_graft_entry():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_graft_entry_for_test", os.path.join(root,
                                              "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_dryrun_moe_ep_metrics_export(tmp_path, monkeypatch):
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    monkeypatch.setenv("PADDLE_TPU_METRICS_DIR", str(tmp_path))
    ge = _load_graft_entry()
    try:
        loss = ge._moe_train_step(2, tag="telemetry-smoke")
        assert np.isfinite(loss)
    finally:
        # don't leak the EP mesh into later tests
        from paddle_tpu.distributed import env as denv
        denv.set_mesh(None)

    from paddle_tpu import monitor
    path = monitor.export_jsonl()
    assert path and os.path.exists(path)
    recs = [json.loads(line) for line in open(path)]
    assert recs, "metrics JSONL is empty"
    names = {r["name"] for r in recs}

    # collective census keys are present and name the EP all-to-alls
    assert "step_collectives" in names
    assert "step_collective_bytes" in names
    assert "step_collective_ops" in names
    a2a = [r for r in recs if r["name"] == "step_collectives"
           and r["labels"].get("op") == "all_to_all"
           and r["labels"].get("axis") == "ep"]
    assert a2a and a2a[0]["value"] > 0
    a2a_bytes = [r for r in recs if r["name"] == "step_collective_bytes"
                 and r["labels"].get("op") == "all_to_all"
                 and r["labels"].get("axis") == "ep"]
    assert a2a_bytes and a2a_bytes[0]["value"] > 0

    # compiled-step accounting landed too
    assert "step_flops" in names
    flops = [r for r in recs if r["name"] == "step_flops"
             and "Qwen2Moe" in r["labels"].get("step", "")]
    assert flops and flops[0]["value"] > 0

    # and the MoE path counters are served through the same registry
    assert "moe_path_calls" in names
