"""MoE in production (ISSUE 8): fused-dispatch grouped matmul parity
(fwd + VJP, interpret mode) and MoE through the paged/ragged serving
engine — Qwen2-MoE/DeepSeek-MoE greedy token-exact vs the dense cached
forward, spec-ngram on dropless MoE, zero steady-state recompiles,
kill switch, validation, telemetry."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.distributed import moe as M


def _routing(rng, s, e, k):
    """Host-side routing fixture shared by the kernel parity tests:
    stable expert-major sort of random top-k picks, exactly the
    dispatch `_grouped_dispatch` derives."""
    flat_e = rng.randint(0, e, s * k).astype(np.int32)
    order = np.argsort(flat_e, kind="stable").astype(np.int32)
    counts = np.bincount(flat_e, minlength=e).astype(np.int32)
    return order, (order // k).astype(np.int32), counts


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_fused_gmm_interpret_parity_fwd(dtype):
    """Gather-on-read + swiglu-epilogue + scatter-on-write kernels
    reproduce the pack+gmm reference (sorted take -> ragged_dot ->
    unsort scatter) under the Pallas interpreter."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import moe_gmm as G

    rng = np.random.RandomState(0)
    s, d, f, e, k = 64, 64, 128, 8, 2
    m = s * k
    dt = jnp.dtype(dtype)
    x = jnp.asarray(rng.randn(s, d), dt)
    gu = jnp.asarray(0.1 * rng.randn(e, d, 2 * f), dt)
    dn = jnp.asarray(0.1 * rng.randn(e, f, d), dt)
    order, src, counts = _routing(rng, s, e, k)
    gs = jnp.asarray(counts)

    xs = jnp.take(x, jnp.asarray(src), axis=0)
    gu_ref = jax.lax.ragged_dot(xs, gu, gs)
    g_, u_ = jnp.split(gu_ref, 2, axis=-1)
    h_ref = (jax.nn.silu(g_.astype(jnp.float32)).astype(dt) * u_)
    ys_ref = jax.lax.ragged_dot(h_ref, dn, gs)
    ys_tok_ref = np.zeros((m, d), np.float32)
    ys_tok_ref[order] = np.asarray(ys_ref, np.float32)

    h = G.gather_gmm_swiglu(x, jnp.asarray(src), gu, gs,
                            interpret=True)
    ys_tok = G.scatter_gmm(h, dn, gs, jnp.asarray(order),
                           interpret=True)
    tol = 1e-5 if dtype == "float32" else 0.1
    np.testing.assert_allclose(np.asarray(h, np.float32),
                               np.asarray(h_ref, np.float32),
                               atol=tol, rtol=tol)
    np.testing.assert_allclose(np.asarray(ys_tok, np.float32),
                               ys_tok_ref, atol=tol, rtol=tol)
    # the plain gather gmm (no epilogue) and the transposed variants
    # the backward replays
    o1 = G.gather_gmm(x, jnp.asarray(src), gu, gs, interpret=True)
    np.testing.assert_allclose(np.asarray(o1, np.float32),
                               np.asarray(gu_ref, np.float32),
                               atol=tol, rtol=tol)
    o2 = G.gather_gmm(jnp.asarray(ys_tok_ref, dt), jnp.asarray(order),
                      dn, gs, transpose_rhs=True, interpret=True)
    ref2 = jax.lax.ragged_dot(jnp.asarray(ys_tok_ref, dt)[order],
                              dn.swapaxes(1, 2), gs)
    np.testing.assert_allclose(np.asarray(o2, np.float32),
                               np.asarray(ref2, np.float32),
                               atol=tol * 30, rtol=tol * 30)


def test_fused_dispatch_parity_fwd_and_vjp():
    """The WIRED fused path (``PADDLE_TPU_MOE_FUSED_GMM=interpret``
    through ``moe_dispatch_combine_dropless``) matches the sorted
    pack+gmm path it replaces — outputs AND all four gradients (x,
    gate_up, down, router logits), i.e. the custom VJP replaying
    gather/scatter backward is the same function."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    s, d, f, e, k = 128, 128, 128, 8, 2
    x = jnp.asarray(rng.randn(s, d).astype(np.float32))
    logits = jnp.asarray(rng.randn(s, e).astype(np.float32))
    gu = jnp.asarray((0.1 * rng.randn(e, d, 2 * f)).astype(np.float32))
    dn = jnp.asarray((0.1 * rng.randn(e, f, d)).astype(np.float32))

    def loss(x, gu, dn, logits):
        y, aux = M.moe_dispatch_combine_dropless(x, logits, e, k, gu,
                                                 dn)
        return jnp.sum(y * y) + aux, y

    grad = jax.value_and_grad(loss, argnums=(0, 1, 2, 3),
                              has_aux=True)
    old = os.environ.get("PADDLE_TPU_MOE_FUSED_GMM")
    try:
        os.environ["PADDLE_TPU_MOE_FUSED_GMM"] = "0"
        (l0, y0), g0 = grad(x, gu, dn, logits)
        os.environ["PADDLE_TPU_MOE_FUSED_GMM"] = "interpret"
        (l1, y1), g1 = grad(x, gu, dn, logits)
        assert M.MOE_STATS["grouped_mm_kernel"] is not None
    finally:
        if old is None:
            os.environ.pop("PADDLE_TPU_MOE_FUSED_GMM", None)
        else:
            os.environ["PADDLE_TPU_MOE_FUSED_GMM"] = old
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               atol=1e-4, rtol=1e-4)
    for name, a, b in zip(("dx", "dgate_up", "ddown", "dlogits"), g0,
                          g1):
        scale = max(float(jnp.abs(a).max()), 1.0)
        np.testing.assert_allclose(
            np.asarray(b) / scale, np.asarray(a) / scale,
            atol=2e-5, rtol=2e-5, err_msg=name)


def test_fused_kernel_reflects_in_moe_stats():
    """A forward through the fused path stamps ``MOE_STATS`` with the
    fused kernel name at trace time (the bench/ops 'which kernel did I
    compile' contract extends to the fused engine)."""
    import jax.numpy as jnp

    rng = np.random.RandomState(2)
    s, d, f, e, k = 128, 128, 128, 4, 2
    x = jnp.asarray(rng.randn(s, d).astype(np.float32))
    logits = jnp.asarray(rng.randn(s, e).astype(np.float32))
    gu = jnp.asarray((0.1 * rng.randn(e, d, 2 * f)).astype(np.float32))
    dn = jnp.asarray((0.1 * rng.randn(e, f, d)).astype(np.float32))
    old = os.environ.get("PADDLE_TPU_MOE_FUSED_GMM")
    try:
        os.environ["PADDLE_TPU_MOE_FUSED_GMM"] = "interpret"
        M.reset_moe_stats()
        M.moe_dispatch_combine_dropless(x, logits, e, k, gu, dn)
        assert M.MOE_STATS["grouped_mm_kernel"] == "fused_gmm"
        assert M.MOE_STATS["grouped_mm_calls"] >= 2
    finally:
        if old is None:
            os.environ.pop("PADDLE_TPU_MOE_FUSED_GMM", None)
        else:
            os.environ["PADDLE_TPU_MOE_FUSED_GMM"] = old


def test_fused_kill_switch_bit_exact():
    """``PADDLE_TPU_MOE_FUSED_GMM=0`` pins the sort->pack->gmm path
    bit-for-bit: it wins over the config/env fused request (the fused
    kernels are never traced), and the output is BITWISE the default
    CPU path's."""
    import jax.numpy as jnp

    rng = np.random.RandomState(3)
    s, d, f, e, k = 128, 128, 128, 4, 2
    x = jnp.asarray(rng.randn(s, d).astype(np.float32))
    logits = jnp.asarray(rng.randn(s, e).astype(np.float32))
    gu = jnp.asarray((0.1 * rng.randn(e, d, 2 * f)).astype(np.float32))
    dn = jnp.asarray((0.1 * rng.randn(e, f, d)).astype(np.float32))
    old = os.environ.get("PADDLE_TPU_MOE_FUSED_GMM")
    try:
        os.environ.pop("PADDLE_TPU_MOE_FUSED_GMM", None)
        y_default, _ = M.moe_dispatch_combine_dropless(
            x, logits, e, k, gu, dn)
        os.environ["PADDLE_TPU_MOE_FUSED_GMM"] = "0"
        assert not M.moe_fused_enabled()
        # the kill switch beats an explicit fused=True request
        assert M._use_fused_gmm(s * k, d, f, fused=True) is False
        M.reset_moe_stats()
        y_killed, _ = M.moe_dispatch_combine_dropless(
            x, logits, e, k, gu, dn, fused=True)
        assert M.MOE_STATS["grouped_mm_kernel"] == "ragged_dot"
        assert (np.asarray(y_killed) == np.asarray(y_default)).all()
    finally:
        if old is None:
            os.environ.pop("PADDLE_TPU_MOE_FUSED_GMM", None)
        else:
            os.environ["PADDLE_TPU_MOE_FUSED_GMM"] = old


# ---------------------------------------------------------------------------
# serving
# ---------------------------------------------------------------------------

def _tiny_qwen2_moe(dropless=True, **kw):
    from paddle_tpu.models.qwen2_moe import (Qwen2MoeConfig,
                                             Qwen2MoeForCausalLM)
    paddle.seed(7)
    cfg = Qwen2MoeConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                              kv_heads=2, moe_ffn=32, shared_ffn=48,
                              experts=4, topk=2)
    cfg.dropless = dropless
    for k_, v in kw.items():
        setattr(cfg, k_, v)
    m = Qwen2MoeForCausalLM(cfg)
    m.eval()
    return m


def _dense_refs(model, prompts, max_new):
    outs = []
    for p in prompts:
        out, _ = model.generate(
            paddle.to_tensor(p[None].astype(np.int64)),
            max_new_tokens=max_new, cache_impl="dense",
            decode_strategy="greedy_search")
        outs.append(np.asarray(out.numpy())[0])
    return outs


def test_qwen2_moe_engine_greedy_exact_ragged_on_off():
    """Qwen2-MoE (dropless) serves through ``ServingEngine`` — paged +
    ragged paths — greedy token-exact vs ``generate(
    cache_impl="dense")``, with the ragged and legacy per-width paths
    agreeing."""
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model = _tiny_qwen2_moe()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
               for n in (5, 9, 13)]
    refs = _dense_refs(model, prompts, 6)
    for ragged in (True, False):
        eng = ServingEngine(model, ServingConfig(
            num_slots=3, block_size=4, max_model_len=64,
            max_new_tokens=6, prefill_chunk=8, ragged_batch=ragged))
        outs = eng.serve([p.copy() for p in prompts], max_new_tokens=6)
        st = eng.stats()
        eng.shutdown()
        for o, r in zip(outs, refs):
            assert (np.asarray(o) == r).all(), (ragged, o, r)
        assert st["moe"] is True
        assert st["moe_dispatches"] > 0
        assert st["moe_routing_entropy"] > 0.0
        assert st["moe_expert_load_max"] > 0.0


def test_deepseek_moe_engine_greedy_exact():
    """DeepSeek-MoE (fine-grained experts + ungated shared experts,
    first layer dense) through the engine == dense cached forward."""
    from paddle_tpu.models.deepseek_moe import (DeepseekMoeConfig,
                                                DeepseekMoeForCausalLM)
    from paddle_tpu.inference import ServingConfig, ServingEngine
    paddle.seed(5)
    cfg = DeepseekMoeConfig.tiny(vocab=128, hidden=64, layers=2,
                                 heads=4, kv_heads=4, moe_ffn=32,
                                 dense_ffn=48, experts=4, shared=1,
                                 topk=2)
    cfg.dropless = True
    model = DeepseekMoeForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
               for n in (6, 11)]
    refs = _dense_refs(model, prompts, 5)
    eng = ServingEngine(model, ServingConfig(
        num_slots=2, block_size=4, max_model_len=64, max_new_tokens=5,
        prefill_chunk=8))
    outs = eng.serve([p.copy() for p in prompts], max_new_tokens=5)
    eng.shutdown()
    for o, r in zip(outs, refs):
        assert (np.asarray(o) == r).all()


def test_spec_ngram_on_dropless_moe_token_exact():
    """The speculative-verify exclusion lifts for dropless MoE: a
    gamma=2 n-gram engine emits exactly the plain engine's greedy
    chain (per-row dropless routing cannot see the other window
    rows)."""
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model = _tiny_qwen2_moe()
    rng = np.random.RandomState(2)
    phrase = rng.randint(1, 128, (4,))
    prompts = [np.tile(phrase, 4).astype(np.int32) for _ in range(3)]
    eng = ServingEngine(model, ServingConfig(
        num_slots=2, block_size=4, max_model_len=64, max_new_tokens=8,
        prefill_chunk=8))
    refs = eng.serve([p.copy() for p in prompts], max_new_tokens=8)
    eng.shutdown()
    eng2 = ServingEngine(model, ServingConfig(
        num_slots=2, block_size=4, max_model_len=64, max_new_tokens=8,
        prefill_chunk=8, num_speculative_tokens=2))
    outs = eng2.serve([p.copy() for p in prompts], max_new_tokens=8)
    st = eng2.stats()
    eng2.shutdown()
    assert st["spec_tokens_proposed"] > 0
    for o, r in zip(outs, refs):
        assert (np.asarray(o) == np.asarray(r)).all()


def test_moe_engine_zero_steady_state_recompiles():
    """The ragged MoE engine compiles ONE executable and serves two
    request waves (fresh admissions mid-flight) without ever building
    another."""
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model = _tiny_qwen2_moe()
    rng = np.random.RandomState(3)
    eng = ServingEngine(model, ServingConfig(
        num_slots=2, block_size=4, max_model_len=64, max_new_tokens=6,
        prefill_chunk=8))
    eng.serve([rng.randint(1, 128, (n,)).astype(np.int32)
               for n in (5, 9)], max_new_tokens=6)
    st0 = eng.stats()
    assert st0["executables_compiled"] == 1
    eng.serve([rng.randint(1, 128, (n,)).astype(np.int32)
               for n in (12, 4, 8)], max_new_tokens=6)
    st1 = eng.stats()
    eng.shutdown()
    assert st1["executables_compiled"] == st0["executables_compiled"]
    assert st1["decode_compiles"] == 1


def test_capacity_moe_engine_rejected():
    """Capacity-routed MoE stays excluded from serving, with an error
    that names the fix (dropless routing) — never a silent wrong
    logit."""
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model = _tiny_qwen2_moe(dropless=False)
    with pytest.raises(NotImplementedError, match="dropless"):
        ServingEngine(model, ServingConfig(num_slots=2,
                                           max_model_len=64))


def test_moe_tp_divisibility_validated():
    """``tp_degree`` must divide ``moe_intermediate_size`` (the
    stacked expert ffn shard dim) — rejected at engine construction,
    before any compile."""
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model = _tiny_qwen2_moe(moe_intermediate_size=33)
    # heads (4), kv_heads (2) and vocab (128) all divide 2; the expert
    # width (33) does not — the MoE check must be the one that fires
    with pytest.raises(ValueError, match="moe_intermediate_size"):
        ServingEngine(model, ServingConfig(num_slots=2,
                                           max_model_len=64,
                                           tp_degree=2))


def test_moe_engine_tp2_token_exact():
    """Dropless MoE under tensor-parallel serving (tp_degree=2 on the
    8-CPU-device mesh): stacked expert weights shard their ffn dim
    over mp, the dispatch takes the GSPMD ragged_dot lowering (opaque
    Pallas kernels stay off sharded traces), and greedy tokens equal
    the single-device engine's."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model = _tiny_qwen2_moe()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
               for n in (5, 10)]
    eng = ServingEngine(model, ServingConfig(
        num_slots=2, block_size=4, max_model_len=64, max_new_tokens=5,
        prefill_chunk=8))
    refs = eng.serve([p.copy() for p in prompts], max_new_tokens=5)
    eng.shutdown()
    eng_tp = ServingEngine(model, ServingConfig(
        num_slots=2, block_size=4, max_model_len=64, max_new_tokens=5,
        prefill_chunk=8, tp_degree=2))
    outs = eng_tp.serve([p.copy() for p in prompts], max_new_tokens=5)
    st = eng_tp.stats()
    eng_tp.shutdown()
    assert st["tp_degree"] == 2 and st["moe"] is True
    assert st["moe_dispatches"] > 0      # the tap observes under TP too
    for o, r in zip(outs, refs):
        assert (np.asarray(o) == np.asarray(r)).all()


def test_moe_stats_keys_always_present_and_jsonl(tmp_path):
    """The moe_* stats keys exist on NON-MoE engines too (False/0.0 —
    mixed fleets never KeyError), and the routing metrics land in the
    JSONL export."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.inference import ServingConfig, ServingEngine
    paddle.seed(0)
    dense = LlamaForCausalLM(LlamaConfig.tiny(vocab=64, hidden=32,
                                              layers=1, heads=4,
                                              kv_heads=2, ffn=64))
    dense.eval()
    eng = ServingEngine(dense, ServingConfig(
        num_slots=1, block_size=4, max_model_len=32, max_new_tokens=3,
        prefill_chunk=4))
    eng.serve([np.asarray([1, 2, 3], np.int32)], max_new_tokens=3)
    st = eng.stats()
    eng.shutdown()
    for key in ("moe", "moe_fused_gmm", "moe_routing_entropy",
                "moe_expert_load_max", "moe_dispatches"):
        assert key in st, key
    assert st["moe"] is False
    assert st["moe_dispatches"] == 0

    model = _tiny_qwen2_moe()
    eng2 = ServingEngine(model, ServingConfig(
        num_slots=1, block_size=4, max_model_len=32, max_new_tokens=3,
        prefill_chunk=4))
    eng2.serve([np.asarray([3, 2, 1], np.int32)], max_new_tokens=3)
    st2 = eng2.stats()
    eng2.shutdown()
    assert st2["moe"] is True and st2["moe_dispatches"] > 0
    # honest fused stat: reports whether the fused kernel actually
    # TRACED into an executable — never on a CPU backend
    assert st2["moe_fused_gmm"] is False
    path = monitor.export_jsonl(str(tmp_path / "metrics.jsonl"))
    names = {json.loads(line)["name"] for line in open(path)}
    assert "serving_moe_expert_load" in names
    assert "serving_moe_routing_entropy" in names
    # telemetry opt-out: executables trace without the tap — zero
    # callbacks, keys still present
    eng3 = ServingEngine(model, ServingConfig(
        num_slots=1, block_size=4, max_model_len=32, max_new_tokens=3,
        prefill_chunk=4, moe_telemetry=False))
    eng3.serve([np.asarray([2, 3, 4], np.int32)], max_new_tokens=3)
    st3 = eng3.stats()
    eng3.shutdown()
    assert st3["moe_dispatches"] == 0
    assert st3["moe_routing_entropy"] == 0.0


def test_routing_tap_masks_pad_rows():
    """The serving telemetry tap counts LIVE rows only: with a
    ``serving_rows_mask`` armed, pad rows of the fixed-shape serving
    buffers (which all route identically) are excluded, so a lightly
    loaded tick cannot read as hot-expert skew."""
    import jax.numpy as jnp

    captured = []

    def sink(load, ent):
        captured.append((np.asarray(load), float(ent)))

    s, d, f, e, k = 8, 16, 16, 4, 2
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(s, d).astype(np.float32))
    # live rows 0..3 route to experts {1, 2}; pad rows 4..7 to {0, 3}
    logits = np.full((s, e), -10.0, np.float32)
    logits[:4, 1] = 5.0
    logits[:4, 2] = 4.0
    logits[4:, 0] = 5.0
    logits[4:, 3] = 4.0
    gu = jnp.asarray((0.1 * rng.randn(e, d, 2 * f)).astype(np.float32))
    dn = jnp.asarray((0.1 * rng.randn(e, f, d)).astype(np.float32))
    mask = jnp.asarray([True] * 4 + [False] * 4)
    with M.serving_stats_tap(sink), M.serving_rows_mask(mask):
        y, _ = M.moe_dispatch_combine_dropless(
            x, jnp.asarray(logits), e, k, gu, dn)
    np.asarray(y)                      # force execution -> callback
    assert captured, "tap did not fire"
    load, ent = captured[0]
    assert load[0] == 0.0 and load[3] == 0.0, load   # pads excluded
    np.testing.assert_allclose(load[1], 0.5, atol=1e-6)
    np.testing.assert_allclose(load[2], 0.5, atol=1e-6)
    # without the mask the pad experts would dominate the same tick
    captured.clear()
    with M.serving_stats_tap(sink):
        y2, _ = M.moe_dispatch_combine_dropless(
            x, jnp.asarray(logits), e, k, gu, dn)
    np.asarray(y2)
    assert captured[0][0][0] > 0.0


def test_generate_bucketing_lifted_for_dropless_moe():
    """Prompt bucketing (PR 3's capacity-MoE exclusion) admits
    dropless MoE: left-pad rows route per-row, so pads cannot perturb
    real tokens."""
    model = _tiny_qwen2_moe()
    assert model._bucket_eligible()
    assert not _tiny_qwen2_moe(dropless=False)._bucket_eligible()


def test_tier1_no_slow_marker():
    """CI guard (the PR-4..7 pattern): every MoE-serving test runs in
    the tier-1 ``-m 'not slow'`` sweep, the fused-kernel parity tests
    are present, and each engine is torn down through shutdown()'s
    allocator leak sweep."""
    import tests.conftest as c
    here = open(__file__).read()
    assert "pytest.mark.slow" not in here.replace(
        '"pytest.mark.slow"', "")
    names = [ln.split("(")[0][4:] for ln in here.splitlines()
             if ln.startswith("def test_")]
    overlap = set(names) & set(c._SLOW_TESTS)
    assert not overlap, f"tier-1 MoE-serving tests marked slow: {overlap}"
    assert "test_fused_gmm_interpret_parity_fwd" in names
    assert "test_fused_dispatch_parity_fwd_and_vjp" in names
    assert here.count(".shutdown()") >= 6, \
        "engine shutdown (check_leaks) must guard these tests"
