"""Async tick pipeline (ISSUE 20): depth-1 dispatch-ahead with
device-resident decode state. The contract under test is EXACTNESS —
``async_depth=1`` must be greedy token-exact vs ``async_depth=0``
across the whole engine matrix (fp / int8 KV / spec n-gram / spec
tree / LoRA / TP=2 / GPT / colocated + disaggregated cluster),
because the pipelined tick consumes the SAME executable's own carry
outputs instead of a host round-trip. Also pinned here: the
``PADDLE_TPU_ASYNC_TICK`` kill switch (env "0" beats the config, env
"1" arms the default), zero steady-state recompiles across waves
(``executables_compiled`` stays at the ragged baseline of 1),
pipeline flush correctness on every slot-composition event
(admission, preemption, migration, cancel), EOS-overrun tokens
dropped exactly at commit, the non-finite-logits health probe firing
through the NON-blocking fetch, and the new always-present stats
keys (``async_depth`` / ``pipeline_flushes`` / ``host_gap_ms``).

Tier-1 guard: every test here must run in the standard
``-m 'not slow'`` sweep — ``test_tier1_no_slow_marker`` pins that.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ServingConfig, ServingEngine
from paddle_tpu.inference.cluster import ClusterConfig, EngineCluster
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

jnp = pytest.importorskip("jax.numpy")


@pytest.fixture(scope="module")
def llama_tiny():
    paddle.seed(7)
    cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                           kv_heads=2, ffn=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def gpt_tiny():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(11)
    m = GPTForCausalLM(GPTConfig.tiny(vocab=96, hidden=64, layers=2,
                                      heads=4))
    m.eval()
    return m


def _scfg(**kw):
    base = dict(num_slots=2, block_size=8, max_model_len=64,
                prefill_chunk=8, min_prefill_bucket=8)
    base.update(kw)
    return ServingConfig(**base)


def _prompts(vocab=128, lens=(9, 5, 12), seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, (n,)).astype(np.int64) for n in lens]


def _serve(model, prompts, depth, max_new=8, **cfg_kw):
    eng = ServingEngine(model, _scfg(async_depth=depth, **cfg_kw))
    out = eng.serve([p.copy() for p in prompts],
                    max_new_tokens=max_new)
    st = eng.stats()
    eng.shutdown()
    return out, st


def _assert_equal(a, b, tag):
    assert len(a) == len(b), tag
    for i, (x, y) in enumerate(zip(a, b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"{tag} request {i}")


# ------------------------------------------------- parity matrix


@pytest.mark.parametrize("variant", ["fp", "int8", "spec_ngram",
                                     "spec_tree"])
def test_parity_matrix_llama(llama_tiny, variant):
    """async ON == OFF greedy token-exact, per engine variant, with
    the one-executable collapse intact in BOTH modes (the carry
    outputs ride the ONE tick executable — they never add one)."""
    kw = {"fp": {},
          "int8": dict(kv_cache_dtype="int8"),
          "spec_ngram": dict(num_speculative_tokens=2),
          "spec_tree": dict(num_speculative_tokens=2,
                            spec_tree=(0, 1))}[variant]
    on, st_on = _serve(llama_tiny, _prompts(), 1, **kw)
    off, st_off = _serve(llama_tiny, _prompts(), 0, **kw)
    _assert_equal(off, on, f"llama {variant} async on/off")
    assert st_on["async_depth"] == 1 and st_off["async_depth"] == 0
    assert st_on["executables_compiled"] == \
        st_off["executables_compiled"] == 1
    if variant == "fp":             # g==0: the pipeline actually ran
        assert st_on["host_gap_ms"]["count"] > 0
        assert st_on["tokens_total"] == st_off["tokens_total"]


def test_parity_gpt(gpt_tiny):
    """GPT (LayerNorm + fused QKV + biased MLP): same carry graph,
    token-exact."""
    on, st_on = _serve(gpt_tiny, _prompts(vocab=96), 1)
    off, _ = _serve(gpt_tiny, _prompts(vocab=96), 0)
    _assert_equal(off, on, "gpt async on/off")
    assert st_on["executables_compiled"] == 1


def test_parity_lora(llama_tiny):
    """Multi-LoRA: the per-slot adapter row travels IN the carry, so
    a pipelined tick keeps each slot pinned to its adapter."""
    names = ("q_proj", "o_proj")    # square on kv_heads=2 tiny
    rng = np.random.RandomState(101)
    w = {n: (rng.normal(0, 0.3, (64, 4)).astype(np.float32),
             rng.normal(0, 0.3, (4, 64)).astype(np.float32))
         for n in names}
    outs = {}
    for depth in (1, 0):
        eng = ServingEngine(llama_tiny, _scfg(
            async_depth=depth, lora_rank=4, max_adapters=2))
        eng.load_adapter(1, w)
        rids = [eng.submit(p.copy(), 6, adapter_id=a)
                for p, a in zip(_prompts(), (1, None, 1))]
        done = eng.run()
        outs[depth] = [done[r] for r in rids]
        if depth == 1:
            assert eng.stats()["executables_compiled"] == 1
        eng.shutdown()
    _assert_equal(outs[0], outs[1], "lora async on/off")


def test_parity_tp2(llama_tiny):
    """TP=2: carry arrays pinned replicated across the mesh — the
    pipelined dispatch's input shardings match the AOT signature."""
    on, st_on = _serve(llama_tiny, _prompts(), 1, tp_degree=2)
    off, _ = _serve(llama_tiny, _prompts(), 0, tp_degree=2)
    _assert_equal(off, on, "tp2 async on/off")
    assert st_on["tp_degree"] == 2
    assert st_on["executables_compiled"] == 1


@pytest.mark.parametrize("disagg", [False, True])
def test_parity_cluster(llama_tiny, disagg):
    """Cluster dispatch-all-then-commit-all: colocated and
    prefill/decode-disaggregated fleets stay token-exact vs sync
    replica ticking, with the fleet stats roll-ups present."""
    def run(depth):
        scfg = _scfg(async_depth=depth)
        ccfg = ClusterConfig(num_replicas=2,
                             prefill_replicas=1 if disagg else 0)
        cl = EngineCluster(llama_tiny, ccfg, scfg)
        rids = [cl.submit(p.copy(), 6) for p in _prompts()]
        done = cl.run()
        st = cl.stats()
        cl.shutdown()
        return [done[r] for r in rids], st
    on, st_on = run(1)
    off, st_off = run(0)
    _assert_equal(off, on, f"cluster disagg={disagg} async on/off")
    assert st_on["async_depth"] == 1 and st_off["async_depth"] == 0
    assert st_on["executables_compiled"] == \
        st_off["executables_compiled"]
    assert st_off["pipeline_flushes"] == 0


# --------------------------------------------- kill switch / arming


def test_kill_switch_and_env_arming(llama_tiny, monkeypatch):
    """``PADDLE_TPU_ASYNC_TICK=0`` beats ``async_depth=1`` bit-for-bit
    (same tokens, same executable census, depth reported 0), and
    env "1" arms the default (``async_depth=None``) engine."""
    off, st_off = _serve(llama_tiny, _prompts(), 0)
    monkeypatch.setenv("PADDLE_TPU_ASYNC_TICK", "0")
    killed, st_k = _serve(llama_tiny, _prompts(), 1)
    _assert_equal(off, killed, "kill switch vs sync")
    assert st_k["async_depth"] == 0
    assert st_k["pipeline_flushes"] == 0
    assert st_k["executables_compiled"] == st_off["executables_compiled"]
    monkeypatch.setenv("PADDLE_TPU_ASYNC_TICK", "1")
    armed, st_a = _serve(llama_tiny, _prompts(), None)
    _assert_equal(off, armed, "env-armed vs sync")
    assert st_a["async_depth"] == 1


def test_async_depth_validation(llama_tiny):
    with pytest.raises(ValueError, match="async_depth"):
        _scfg(async_depth=2)
    with pytest.raises(ValueError, match="async_depth"):
        _scfg(async_depth=True)
    # explicit depth on the legacy per-width engine is a loud error;
    # the env-armed default silently degrades instead
    with pytest.raises(NotImplementedError, match="async"):
        ServingEngine(llama_tiny, _scfg(async_depth=1,
                                        ragged_batch=False))


# ------------------------------------------------ steady-state pins


def test_zero_steady_state_recompiles_two_waves(llama_tiny):
    """Two waves through one async engine: the executable census is
    pinned at 1 after wave 1 and STAYS 1 — the pipelined dispatch
    reuses the AOT tick executable, never traces a second one."""
    eng = ServingEngine(llama_tiny, _scfg(async_depth=1))
    eng.serve([p.copy() for p in _prompts()], max_new_tokens=6)
    assert eng.stats()["executables_compiled"] == 1
    steps1 = eng.stats()["decode_steps"]
    eng.serve([p.copy() for p in _prompts(seed=5)], max_new_tokens=6)
    st = eng.stats()
    assert st["executables_compiled"] == 1
    assert st["decode_steps"] > steps1
    assert st["host_gap_ms"]["count"] > 0
    eng.shutdown()


# ------------------------------------------------- flush correctness


def test_flush_on_staggered_admission(llama_tiny):
    """A request arriving mid-pipeline flushes (commit the in-flight
    tick) before the admission tick, so the composition every device
    tick sees — and therefore every greedy token — matches the sync
    schedule exactly."""
    def run(depth):
        eng = ServingEngine(llama_tiny, _scfg(async_depth=depth))
        p0, p1 = _prompts(lens=(9, 7))
        rids = [eng.submit(p0.copy(), 10)]
        for _ in range(4):
            eng.step()
        rids.append(eng.submit(p1.copy(), 8))
        done = eng.run()
        st = eng.stats()
        eng.shutdown()
        return [done[r] for r in rids], st
    on, st_on = run(1)
    off, _ = run(0)
    _assert_equal(off, on, "staggered admission async on/off")
    assert st_on["pipeline_flushes"] >= 1


def test_flush_on_preemption_storm(llama_tiny):
    """The canonical preemption workload (one long low-priority
    request, two high-priority arrivals on a 2-slot engine): the
    preemption drains the pipeline first, and the resumed stream is
    token-exact vs the sync engine under the SAME schedule."""
    def run(depth):
        eng = ServingEngine(llama_tiny, _scfg(
            async_depth=depth, max_model_len=96))
        rng = np.random.RandomState(3)
        lo = rng.randint(1, 128, (20,))
        h1, h2 = rng.randint(1, 128, (9,)), rng.randint(1, 128, (7,))
        rids = [eng.submit(lo.copy(), 12, priority=0)]
        for _ in range(4):
            eng.step()
        rids.append(eng.submit(h1.copy(), 12, priority=2))
        rids.append(eng.submit(h2.copy(), 12, priority=2))
        done = eng.run()
        st = eng.stats()
        eng.shutdown()
        return [done[r] for r in rids], st
    on, st_on = run(1)
    off, st_off = run(0)
    _assert_equal(off, on, "preemption storm async on/off")
    assert st_on["preemptions"] >= 1 and st_off["preemptions"] >= 1


def test_migration_flushes_and_stays_token_exact(llama_tiny):
    """export_session mid-pipeline commits the in-flight tick before
    packaging the slot, and admit_migrated flushes the TARGET's
    pipeline before seating — the migrated stream (source tokens +
    target tokens) equals the never-migrated reference."""
    ref, _ = _serve(llama_tiny, _prompts(lens=(9,)), 0, max_new=10)
    got = []
    cb = lambda rid, tok: got.append(int(tok))
    src = ServingEngine(llama_tiny, _scfg(async_depth=1),
                        stream_callback=cb)
    dst = ServingEngine(llama_tiny, _scfg(async_depth=1),
                        stream_callback=cb)
    src.submit(_prompts(lens=(9,))[0].copy(), 10)
    for _ in range(4):
        src.step()
    rec = src.export_session(0)
    assert src.num_active == 0
    assert dst.admit_migrated(rec) is not None
    dst.run()
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(ref[0]),
                                  err_msg="migrated stream")
    assert src.shutdown() and dst.shutdown()


def test_cancel_mid_pipeline(llama_tiny):
    """cancel() drains the pipeline before tearing the slot down: the
    cancelled request's PARTIAL stream (the tokens committed at the
    flush point) and the survivor's full stream both match the sync
    engine under the same schedule."""
    def run(depth):
        eng = ServingEngine(llama_tiny, _scfg(async_depth=depth))
        p0, p1 = _prompts(lens=(9, 7))
        r0 = eng.submit(p0.copy(), 12)
        r1 = eng.submit(p1.copy(), 12)
        for _ in range(4):
            eng.step()
        assert eng.cancel(r0)
        done = eng.run()
        st = eng.stats()
        eng.shutdown(check_leaks=True)
        assert done[r0].size < 12       # actually cut mid-decode
        return [done[r0], done[r1]], st
    on, st_on = run(1)
    off, _ = run(0)
    _assert_equal(off, on, "cancel partial + survivor")
    assert st_on["pipeline_flushes"] >= 1
    assert st_on["requests_cancelled"] == 1


# ------------------------------------------------------ EOS overrun


def test_eos_overrun_token_dropped_exactly(llama_tiny):
    """When EOS lands while tick N+1 is already in flight, the
    overrun token from the retired slot is dropped at commit: async
    output == sync output (which stops at EOS), and the token
    accounting matches — the speculative extra tick leaks nothing."""
    base, _ = _serve(llama_tiny, _prompts(lens=(9,)), 0, max_new=10)
    stream = [int(t) for t in np.asarray(base[0])]
    eos = stream[4]                 # force a mid-stream EOS retire
    on, st_on = _serve(llama_tiny, _prompts(lens=(9,)), 1,
                       max_new=10, eos_token_id=eos)
    off, st_off = _serve(llama_tiny, _prompts(lens=(9,)), 0,
                         max_new=10, eos_token_id=eos)
    _assert_equal(off, on, "eos overrun async on/off")
    assert len(np.asarray(on[0])) < 10      # EOS actually cut it
    assert st_on["tokens_total"] == st_off["tokens_total"]


# ------------------------------------------------- health under async


def test_nonfinite_probe_fires_under_async(llama_tiny):
    """ISSUE 20 satellite: the non-finite-logits probe now rides the
    async copy (fetched at COMMIT, off the dispatch path) — NaN
    params must still trip the page alert under async_depth=1 with
    the executable census unchanged."""
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                           kv_heads=2, ffn=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    import jax
    eng = ServingEngine(m, _scfg(async_depth=1))
    leaves, treedef = jax.tree_util.tree_flatten(eng._params)
    k = max(range(len(leaves)), key=lambda i: leaves[i].size)
    leaves[k] = jnp.full_like(leaves[k], jnp.nan)
    eng._params = jax.tree_util.tree_unflatten(treedef, leaves)
    eng.submit(_prompts(lens=(9,))[0].copy(), 4)
    eng.run()
    st = eng.stats()
    assert st["nonfinite_logits_ticks"] > 0
    assert "nonfinite_logits" in eng.health()["alerts_firing"]
    assert st["executables_compiled"] == 1
    eng.shutdown(check_leaks=False)


# ------------------------------------------------------- stats keys


def test_stats_keys_always_present(llama_tiny):
    """The ISSUE 20 keys are part of the always-present contract: a
    plain SYNC engine and a 1-replica cluster report them (zeros /
    empty digest), so dashboards never KeyError across configs."""
    eng = ServingEngine(llama_tiny, _scfg())
    st = eng.stats()
    assert st["async_depth"] == 0
    assert st["pipeline_flushes"] == 0
    assert st["host_gap_ms"]["count"] >= 0
    eng.shutdown()
    cl = EngineCluster(llama_tiny, ClusterConfig(num_replicas=1),
                       _scfg())
    cst = cl.stats()
    assert cst["async_depth"] == 0 and cst["pipeline_flushes"] == 0
    cl.shutdown()


# ------------------------------------------------------------- guard


def test_tier1_no_slow_marker():
    """CI guard (the PR-4/5 pattern): every async-tick test runs in
    the tier-1 ``-m 'not slow'`` sweep."""
    import tests.conftest as c
    here = open(__file__).read()
    assert "pytest.mark.slow" not in here.replace(
        '"pytest.mark.slow"', "")
    names = [ln.split("(")[0][4:] for ln in here.splitlines()
             if ln.startswith("def test_")]
    overlap = set(names) & set(c._SLOW_TESTS)
    assert not overlap, overlap
