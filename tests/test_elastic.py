"""Elastic / failure detection (reference: ``fleet/elastic/manager.py``
watch loop + launch controller relaunch + checkpoint-resume —
SURVEY §5.3; tested with real subprocesses per the reference pattern)."""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                  ElasticStatus,
                                                  latest_checkpoint,
                                                  resume_or_start,
                                                  save_checkpoint)


def test_elastic_manager_heartbeat_and_death():
    mgr = ElasticManager(rank=0, world_size=2, is_master=True,
                         timeout=1.0)
    try:
        # rank 0 registers + beats; rank 1 (same store, simulated)
        mgr.register()
        peer = ElasticManager(rank=1, world_size=2, is_master=False,
                              port=mgr.port, timeout=1.0)
        peer.register()
        assert sorted(mgr.alive_ranks()) == [0, 1]
        assert mgr.watch() == ElasticStatus.COMPLETED
        # rank 1 stops beating -> declared dead after timeout
        time.sleep(1.2)
        mgr.heartbeat()
        assert mgr.alive_ranks() == [0]
        assert mgr.dead_ranks() == [1]
        peer.close()
    finally:
        mgr.close()


def test_elastic_np_range_hold_vs_restart():
    mgr = ElasticManager(rank=0, world_size=3, is_master=True,
                         np_range=(1, 3), timeout=5.0)
    try:
        mgr.register()
        # 1 of 3 alive, others pending (still starting) -> HOLD
        assert mgr.watch() == ElasticStatus.HOLD
        assert mgr.ready()
        # a DEAD rank (registered, stale beat) below np_min -> RESTART
        strict = ElasticManager(rank=2, world_size=3, is_master=False,
                                port=mgr.port, np_range=(3, 3),
                                timeout=0.3)
        time.sleep(0.5)  # rank 0's beat goes stale for `strict`
        polled = strict.poll()
        assert polled["dead"] == [0] and polled["pending"] == [1, 2]
        assert strict.watch() == ElasticStatus.RESTART
        assert not strict.ready()
        strict.close()
    finally:
        mgr.close()


def test_elastic_finished_ranks_not_dead():
    """A deregistered (cleanly exited) rank is 'finished', never
    triggering a restart of a completing job."""
    mgr = ElasticManager(rank=0, world_size=2, is_master=True,
                         timeout=0.5)
    try:
        mgr.register()
        peer = ElasticManager(rank=1, world_size=2, is_master=False,
                              port=mgr.port, timeout=0.5)
        peer.register()
        peer.deregister()   # clean exit
        time.sleep(0.7)     # peer's beat is stale now
        mgr.heartbeat()
        polled = mgr.poll()
        assert polled["alive"] == [0]
        assert polled["finished"] == [1]
        assert polled["dead"] == []
        assert mgr.watch() != ElasticStatus.RESTART
        peer.close()
    finally:
        mgr.close()


def test_checkpoint_resume_roundtrip(tmp_path):
    import paddle_tpu.nn as nn
    paddle.seed(0)
    model = nn.Linear(4, 4)
    state = model.state_dict()
    save_checkpoint(str(tmp_path), 10, state)
    save_checkpoint(str(tmp_path), 20, state)
    assert latest_checkpoint(str(tmp_path)).endswith("checkpoint-20")

    paddle.seed(1)
    model2 = nn.Linear(4, 4)  # different init
    state2 = model2.state_dict()
    step = resume_or_start(str(tmp_path), state2)
    assert step == 20
    np.testing.assert_allclose(model2.weight.numpy(),
                               model.weight.numpy())


def test_checkpoint_pruning(tmp_path):
    import paddle_tpu.nn as nn
    state = nn.Linear(2, 2).state_dict()
    for s in (1, 2, 3, 4, 5):
        save_checkpoint(str(tmp_path), s, state, keep_last=2)
    names = sorted(os.listdir(tmp_path))
    assert names == ["checkpoint-4", "checkpoint-5"]


def test_resume_reshards_to_current_mesh(tmp_path):
    """Save replicated, resume with the param sharded over a 4-way mesh
    (the restart-on-different-mesh story)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import paddle_tpu.nn as nn
    paddle.seed(3)
    model = nn.Linear(8, 8)
    save_checkpoint(str(tmp_path), 7, model.state_dict())

    paddle.seed(4)
    model2 = nn.Linear(8, 8)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sharding",))
    sharded = NamedSharding(mesh, P("sharding", None))
    model2.weight._data = jax.device_put(
        jnp.asarray(model2.weight.numpy()), sharded)
    step = resume_or_start(str(tmp_path), model2.state_dict())
    assert step == 7
    np.testing.assert_allclose(model2.weight.numpy(),
                               model.weight.numpy())
    assert model2.weight._data.sharding == sharded


def test_launch_elastic_restart(tmp_path):
    """Worker crashes on attempt 0, succeeds on attempt 1; the launch
    controller must relaunch and exit 0 (reference: controller watch
    loop + elastic relaunch)."""
    script = tmp_path / "worker.py"
    marker = tmp_path / "crashed_once"
    script.write_text(
        "import os, sys\n"
        f"m = {str(marker)!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').write('x')\n"
        "    sys.exit(3)\n"
        "print('recovered attempt', os.environ['PADDLE_RESTART_ATTEMPT'])\n"
    )
    log_dir = tmp_path / "logs"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restarts", "1",
         "--log_dir", str(log_dir), str(script)],
        cwd="/root/repo", capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "elastic restart 1/1" in r.stderr
    assert (log_dir / "workerlog.1.1").exists()  # attempt-1 log


def test_launch_failure_exhausts_restarts(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(5)\n")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restarts", "1",
         "--log_dir", str(tmp_path / "logs"), str(script)],
        cwd="/root/repo", capture_output=True, text=True, timeout=120)
    assert r.returncode == 5


def test_env_elastic_heartbeat_wiring(tmp_path):
    """PADDLE_ELASTIC_ENABLE=1 makes init_parallel_env register a
    heartbeating ElasticManager over the native store (multi-process,
    reference driver/worker pattern)."""
    script = tmp_path / "rank.py"
    script.write_text(
        "import os, time\n"
        "import paddle_tpu.distributed as dist\n"
        "from paddle_tpu.distributed import env as denv\n"
        "e = denv.init_parallel_env()\n"
        "mgr = getattr(e, 'elastic_manager', None)\n"
        "assert mgr is not None\n"
        "time.sleep(1.0)\n"
        "assert 0 in mgr.alive_ranks()\n"
        "print('HEARTBEAT-OK', mgr.alive_ranks())\n"
    )
    env = dict(os.environ)
    env.update({"PADDLE_ELASTIC_ENABLE": "1",
                "PADDLE_TRAINER_ID": "0",
                "PADDLE_TRAINERS_NUM": "2",
                "PADDLE_ELASTIC_PORT": "0",
                "PADDLE_ELASTIC_BEAT_S": "0.2",
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": "/root/repo"})
    env.pop("PADDLE_MASTER", None)
    r = subprocess.run([sys.executable, str(script)], env=env,
                       capture_output=True, text=True, timeout=120)
    assert "HEARTBEAT-OK" in r.stdout, r.stderr


def test_launch_hang_detection_restarts(tmp_path):
    """A rank that hangs (stops heartbeating without exiting) must be
    detected by the controller's ElasticManager watch loop and the pod
    restarted (--elastic_level 1)."""
    script = tmp_path / "hang.py"
    marker = tmp_path / "hung_once"
    script.write_text(
        "import os, sys, time\n"
        "from paddle_tpu.distributed import env as denv\n"
        "e = denv.init_parallel_env()\n"
        f"m = {str(marker)!r}\n"
        "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "if rank == 1 and not os.path.exists(m):\n"
        "    open(m, 'w').write('x')\n"
        "    e.elastic_manager._stop_beat = True  # beats stop; hangs\n"
        "    time.sleep(600)\n"
        # healthy ranks outlive the 2s detection window so the
        # heartbeat watcher (not an exit code) fails the pod
        "time.sleep(8.0)\n"
        "print('DONE', rank)\n"
    )
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": "/root/repo",
                "PADDLE_ELASTIC_BEAT_S": "0.2",
                # faulthandler stabilizes child signal handling when
                # spawned from a pytest(+jax) parent; without it the
                # worker's clean exit intermittently SIGSEGVs (exit-time
                # only — the controller still restarts via exit code,
                # but then this test's heartbeat-path assertion races)
                "PYTHONFAULTHANDLER": "1"})
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restarts", "1",
         "--elastic_level", "1", "--elastic_timeout", "2",
         "--log_dir", str(tmp_path / "logs"), str(script)],
        env=env, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "heartbeat lost" in r.stderr
    assert "elastic restart 1/1" in r.stderr


@pytest.mark.slow
def test_master_failover_snapshot_resume(tmp_path):
    """Kill rank-0 (the store master) with SIGKILL and relaunch it: the
    persisted store snapshot must restore the elastic state (worker
    registrations survive), and training resumes from the checkpoint
    (r3 verdict #9 — etcd-durability parity without etcd)."""
    snap = str(tmp_path / "store.snapshot")
    ckpt = str(tmp_path / "ckpt")
    script = tmp_path / "master.py"
    script.write_text(f"""
import sys, time
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                  save_checkpoint)
mgr = ElasticManager(rank=0, world_size=2, is_master=True,
                     snapshot_path={snap!r}, timeout=5.0)
mgr.register()
# job metadata a restarted master must recover
mgr._store.set("elastic/job/world_size", "2")
# train a step and checkpoint
paddle.seed(0)
w = paddle.to_tensor(np.full((4,), 7.25, np.float32))
save_checkpoint({ckpt!r}, step=3, state_dict={{"w": w}})
print("PORT", mgr.port, flush=True)
time.sleep(120)   # parent SIGKILLs us here
""")
    env = {k: v for k, v in os.environ.items()}
    env["PYTHONPATH"] = os.getcwd()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    proc = subprocess.Popen([sys.executable, str(script)], env=env,
                            stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert line.startswith("PORT"), line
        # a worker registers against the live master
        worker = ElasticManager(rank=1, world_size=2, is_master=False,
                                port=int(line.split()[1]), timeout=5.0)
        worker.register()
        worker.close()
        time.sleep(0.3)          # let the snapshot land
        proc.kill()              # SIGKILL: no cleanup, no close()
        proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()

    # ---- relaunched master: same snapshot, fresh process state ----
    mgr2 = ElasticManager(rank=0, world_size=2, is_master=True,
                          snapshot_path=snap, timeout=1e9)
    try:
        polled = mgr2.poll()
        regs = sorted(polled["alive"] + polled["dead"])
        assert regs == [0, 1], (
            f"registrations lost across master restart: {polled}")
        assert mgr2._store.try_get("elastic/job/world_size") == b"2"
    finally:
        mgr2.close()

    # training resumes from the persisted checkpoint
    state = {"w": paddle.to_tensor(np.zeros((4,), np.float32))}
    step = resume_or_start(ckpt, state)
    assert step == 3
    np.testing.assert_allclose(state["w"].numpy(), np.full((4,), 7.25))
