"""MoE gate semantics + ZeRO stage-2 (reference test strategy:
``test/collective/fleet`` gate/sharding suites — gates must be
behaviorally distinct, stage-2 must train at parity with stage-1)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import env as denv
from paddle_tpu.distributed.moe import (ClipGradForMOEByGlobalNorm,
                                        GShardGate, MoELayer, NaiveGate,
                                        SwitchGate, moe_dispatch_combine)


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    denv.set_mesh(None)
    from paddle_tpu.distributed.fleet.topology import set_hcg
    set_hcg(None)
    import paddle_tpu.distributed.fleet as _fleet
    _fleet._strategy = None


def _experts(n, d=16, h=32):
    return [nn.Sequential(nn.Linear(d, h), nn.GELU(), nn.Linear(h, d))
            for _ in range(n)]


def test_switch_gate_is_top1_with_train_jitter():
    paddle.seed(0)
    g = SwitchGate(16, 4, switch_eps=0.5)
    assert g.top_k == 1
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(32, 16).astype(np.float32))
    g.train()
    a = g(x).numpy()
    b = g(x).numpy()  # fresh jitter draw -> different logits
    assert not np.allclose(a, b)
    g.eval()
    c = g(x).numpy()
    d = g(x).numpy()
    np.testing.assert_allclose(c, d)


def test_gshard_random_second_expert_drops_some():
    """With random routing, slot-1 dispatch probability is min(1, 2*g2):
    skewed gates must drop part of the 2nd-expert traffic; policy='all'
    keeps everything that fits capacity."""
    rng = np.random.RandomState(1)
    s, e = 512, 4
    x = jnp.asarray(rng.randn(s, 8).astype(np.float32))
    # logits skewed: top-1 prob ~0.85, top-2 ~0.1 -> keep2 ~ 0.2
    logits = jnp.asarray(
        np.tile(np.array([[4.0, 2.0, 0.0, 0.0]], np.float32), (s, 1)))
    efn = lambda t: t  # identity experts

    _, _, st_all = moe_dispatch_combine(
        x, logits, e, top_k=2, capacity_factor=8.0, expert_fn=efn,
        second_expert_policy="all", return_stats=True)
    _, _, st_rand = moe_dispatch_combine(
        x, logits, e, top_k=2, capacity_factor=8.0, expert_fn=efn,
        second_expert_policy="random", rng_key=jax.random.PRNGKey(0),
        return_stats=True)
    drop_all = float(st_all["drop_rate"])
    drop_rand = float(st_rand["drop_rate"])
    assert drop_all == pytest.approx(0.0, abs=1e-6)
    # ~half of slot-1 dispatches skipped -> drop_rate ~0.25 of (s*k)
    assert 0.05 < drop_rand < 0.45


def test_capacity_overflow_reported():
    rng = np.random.RandomState(2)
    s, e = 128, 4
    x = jnp.asarray(rng.randn(s, 8).astype(np.float32))
    # all tokens want expert 0 -> tiny capacity drops most
    logits = jnp.asarray(
        np.tile(np.array([[9.0, 0.0, 0.0, 0.0]], np.float32), (s, 1)))
    _, _, st = moe_dispatch_combine(
        x, logits, e, top_k=1, capacity_factor=0.25, expert_fn=lambda t: t,
        return_stats=True)
    assert float(st["drop_rate"]) > 0.5


def test_three_gates_distinct_in_layer():
    paddle.seed(3)
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(4, 8, 16).astype(np.float32))
    outs = {}
    for gtype in ("naive", "gshard", "switch"):
        paddle.seed(42)  # identical expert/gate init
        moe = MoELayer(d_model=16, experts=_experts(4),
                       gate={"type": gtype, "top_k": 2})
        moe.train()
        outs[gtype] = moe(x).numpy()
        assert moe.drop_rate is not None
    # switch is top-1 + jitter, gshard randomly skips 2nd expert ->
    # all three differ pairwise
    assert not np.allclose(outs["naive"], outs["switch"])
    assert not np.allclose(outs["naive"], outs["gshard"])
    assert not np.allclose(outs["gshard"], outs["switch"])


def test_moe_clip_matches_global_norm_and_splits():
    rng = np.random.RandomState(4)
    params = []
    for i, is_exp in enumerate([False, True, True, False]):
        p = paddle.to_tensor(rng.randn(4, 4).astype(np.float32))
        p.is_expert_param = is_exp
        g = paddle.to_tensor(10 * rng.randn(4, 4).astype(np.float32))
        params.append((p, g))
    clip_moe = ClipGradForMOEByGlobalNorm(1.0)
    clip_ref = nn.ClipGradByGlobalNorm(1.0)
    out_moe = clip_moe(list(params))
    out_ref = clip_ref(list(params))
    for (_, gm), (_, gr) in zip(out_moe, out_ref):
        np.testing.assert_allclose(gm.numpy(), gr.numpy(), rtol=1e-6)


def _train_llama(stage, steps=3):
    from paddle_tpu.distributed.fleet import DistributedStrategy, fleet
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                        "sharding_degree": 4, "sep_degree": 1}
    s.sharding_configs = {"sharding_degree": 4, "stage": stage}
    fleet.init(is_collective=True, strategy=s)
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=256, hidden=64, layers=2, heads=4,
                           kv_heads=2, ffn=128)
    model = fleet.distributed_model(LlamaForCausalLM(cfg))
    inner = getattr(model, "_layers", model)
    inner.train()
    opt = fleet.distributed_optimizer(
        paddle.optimizer.AdamW(1e-3, parameters=inner.parameters()))
    if stage >= 2:
        assert getattr(opt._inner, "_shard_grads", False)
    step = TrainStep(inner, lambda out, a, k: out, opt._inner)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (8, 16)).astype(np.int64)
    return [float(step(paddle.to_tensor(ids),
                       paddle.to_tensor(ids)).numpy())
            for _ in range(steps)]


def test_zero_stage2_trains_at_parity_with_stage1():
    l1 = _train_llama(stage=1)
    denv.set_mesh(None)
    from paddle_tpu.distributed.fleet.topology import set_hcg
    set_hcg(None)
    l2 = _train_llama(stage=2)
    assert all(np.isfinite(l2))
    assert l2[-1] < l2[0]
    np.testing.assert_allclose(l1, l2, rtol=2e-4)


def test_group_sharded_parallel_stage2_and_scaler():
    from jax.sharding import Mesh
    from paddle_tpu.distributed.sharding import (GroupShardedScaler,
                                                 group_sharded_parallel)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("sharding",))
    denv.set_mesh(mesh)
    model = nn.Linear(8, 8)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    scaler = paddle.amp.GradScaler()
    m2, o2, s2 = group_sharded_parallel(model, opt, "os_g", scaler=scaler)
    assert getattr(o2, "_shard_grads", False)
    assert isinstance(s2, GroupShardedScaler)
    assert s2.is_enable() == scaler.is_enable()


def test_grouped_capacity_matches_padded_with_real_drops():
    """The r6 grouped-matmul CAPACITY engine must reproduce the padded
    einsum path exactly — including WHICH (token, slot) pairs the
    capacity rule drops (earlier arrivals win) — at a capacity factor
    tight enough to force real drops."""
    from paddle_tpu.distributed.moe import (moe_dispatch_combine,
                                            moe_dispatch_combine_grouped)
    rng = np.random.RandomState(5)
    s, e, d, f, k = 64, 4, 16, 24, 2
    x = jnp.asarray(rng.randn(s, d).astype(np.float32))
    # skew the router so expert 0 overflows its capacity
    logits = jnp.asarray(
        (rng.randn(s, e) + np.array([3.0, 0, 0, 0])).astype(np.float32))
    gate_up = jnp.asarray(0.1 * rng.randn(e, d, 2 * f).astype(np.float32))
    down = jnp.asarray(0.1 * rng.randn(e, f, d).astype(np.float32))

    def efn(expert_in):
        gu = jnp.einsum("ecd,edm->ecm", expert_in, gate_up)
        g, u = jnp.split(gu, 2, axis=-1)
        h = jax.nn.silu(g.astype(jnp.float32)).astype(
            expert_in.dtype) * u
        return jnp.einsum("ecm,emd->ecd", h, down)

    y_pad, aux_pad, st_pad = moe_dispatch_combine(
        x, logits, e, top_k=k, capacity_factor=0.5, expert_fn=efn,
        return_stats=True)
    y_grp, aux_grp, st_grp = moe_dispatch_combine_grouped(
        x, logits, e, k, gate_up, down, capacity_factor=0.5,
        return_stats=True)
    assert float(st_pad["drop_rate"]) > 0.05       # drops really happen
    np.testing.assert_allclose(float(st_grp["drop_rate"]),
                               float(st_pad["drop_rate"]), atol=1e-6)
    np.testing.assert_allclose(np.asarray(y_grp), np.asarray(y_pad),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(float(aux_grp), float(aux_pad),
                               rtol=1e-5)


def test_ep_dropless_vs_capacity_loss_parity():
    """Under an EXPERT-SHARDED mesh, the dropless shard_map fast path
    and the capacity path (padded GSPMD formulation) must train to the
    same loss when capacity is high enough that nothing drops."""
    from paddle_tpu.models.qwen2_moe import (Qwen2MoeConfig,
                                             Qwen2MoeForCausalLM)
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:4]).reshape(4, 1)
    denv.set_mesh(Mesh(devs, ("ep", "mp")))
    try:
        losses = {}
        for dropless in (False, True):
            paddle.seed(11)
            cfg = Qwen2MoeConfig.tiny(vocab=128, hidden=32, layers=1,
                                      heads=4, kv_heads=2, moe_ffn=16,
                                      shared_ffn=32, experts=8, topk=2)
            cfg.capacity_factor = 100.0     # padded path drops nothing
            cfg.dropless = dropless
            cfg.expert_axis = "ep"
            cfg.ep_buffer_factor = 4.0      # == ep degree: no overflow
            model = Qwen2MoeForCausalLM(cfg)
            ids = paddle.to_tensor(np.random.RandomState(2).randint(
                0, 128, (4, 16)).astype(np.int64))
            labels = paddle.to_tensor(
                np.roll(np.asarray(ids.numpy()), -1, axis=1))
            losses[dropless] = float(model(ids, labels=labels).numpy())
        np.testing.assert_allclose(losses[True], losses[False],
                                   rtol=2e-4)
    finally:
        denv.set_mesh(None)
