"""Metric-docs lint guard (ISSUE 11 satellite): every metric name that
registers in the monitor registry at ``import paddle_tpu`` plus the
instantiation of a small serving engine must appear in the docs/OPS.md
metrics table — a new metric can no longer ship undocumented.

The probe runs in a FRESH interpreter so the registry holds exactly the
framework's own registrations (the in-process test suite registers
ad-hoc test metrics that must not trip the lint, and conversely a
polluted registry must not hide a missing doc)."""
import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# import + a small spec-enabled engine (gamma > 0 registers the spec
# metrics too); construction is compile-free, so this stays cheap
_PROBE = """
import json
import paddle_tpu
from paddle_tpu import monitor
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=1, heads=2,
                       kv_heads=1, ffn=64)
m = LlamaForCausalLM(cfg)
m.eval()
from paddle_tpu.inference import ServingConfig, ServingEngine
ServingEngine(m, ServingConfig(num_slots=2, block_size=8,
                               max_model_len=32,
                               num_speculative_tokens=2))
print("METRICS=" + json.dumps(sorted(monitor.get_registry()._metrics)))
"""


def test_every_registered_metric_is_documented():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", _PROBE],
                          capture_output=True, text=True, cwd=_ROOT,
                          env=env, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("METRICS=")][-1]
    names = json.loads(line[len("METRICS="):])
    # sanity: the probe actually saw the registry (serving + jit + moe)
    assert len(names) >= 30, names
    assert "serving_ttft_ms" in names
    with open(os.path.join(_ROOT, "docs", "OPS.md")) as f:
        ops = f.read()
    missing = [n for n in names if n not in ops]
    assert not missing, (
        "metrics registered but undocumented — add them to the "
        f"docs/OPS.md metrics table: {missing}")


# the ISSUE 15 twin: every ALWAYS-present stats() key — the keys a
# PLAIN engine/cluster reports, i.e. the contract dashboards consume —
# must appear as a `code` literal in docs/OPS.md's stats tables. The
# probe builds both compile-free.
_STATS_PROBE = """
import json
import paddle_tpu
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=1, heads=2,
                       kv_heads=1, ffn=64)
m = LlamaForCausalLM(cfg)
m.eval()
from paddle_tpu.inference import ServingConfig, ServingEngine
from paddle_tpu.inference.cluster import ClusterConfig, EngineCluster
scfg = ServingConfig(num_slots=2, block_size=8, max_model_len=32)
eng = ServingEngine(m, scfg)
cl = EngineCluster(m, ClusterConfig(num_replicas=1), scfg)
print("ENGINE_KEYS=" + json.dumps(sorted(eng.stats())))
print("CLUSTER_KEYS=" + json.dumps(sorted(cl.stats())))
"""


def test_every_always_present_stats_key_is_documented():
    """ISSUE 15 satellite: a new always-present ``stats()`` key —
    engine or cluster, roofline/trace keys included — cannot ship
    without a row in an OPS.md stats table (checked as a backticked
    literal so prose words like "active" cannot satisfy the lint)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", _STATS_PROBE],
                          capture_output=True, text=True, cwd=_ROOT,
                          env=env, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    keys = {}
    for tag in ("ENGINE_KEYS", "CLUSTER_KEYS"):
        line = [ln for ln in proc.stdout.splitlines()
                if ln.startswith(tag + "=")][-1]
        keys[tag] = json.loads(line[len(tag) + 1:])
    assert len(keys["ENGINE_KEYS"]) >= 50, keys["ENGINE_KEYS"]
    assert "roofline" in keys["ENGINE_KEYS"]
    assert "trace_events_dropped" in keys["CLUSTER_KEYS"]
    with open(os.path.join(_ROOT, "docs", "OPS.md")) as f:
        ops = f.read()
    missing = sorted({k for ks in keys.values() for k in ks
                      if f"`{k}`" not in ops})
    assert not missing, (
        "always-present stats() keys undocumented — add them to the "
        f"docs/OPS.md stats tables: {missing}")


def test_every_alert_name_is_documented():
    """ISSUE 17 satellite: every alert in the health engine's registry
    must appear as a backticked literal in docs/OPS.md — an alert a
    pager can fire must be explained where the operator will look it
    up. (In-process: ALERT_SEVERITY is a module-level constant, no
    registry pollution to guard against.)"""
    from paddle_tpu.monitor.health import ALERT_SEVERITY
    assert len(ALERT_SEVERITY) >= 10
    assert set(ALERT_SEVERITY.values()) <= {"page", "warn"}
    with open(os.path.join(_ROOT, "docs", "OPS.md")) as f:
        ops = f.read()
    missing = sorted(a for a in ALERT_SEVERITY if f"`{a}`" not in ops)
    assert not missing, (
        "alerts can fire but are undocumented — add them to the "
        f"docs/OPS.md fleet-health section: {missing}")
