"""Fleet flight recorder (ISSUE 15): cross-replica distributed
tracing — merged Chrome/Perfetto trace with one pid per replica,
cluster-global request ids end-to-end, export->import handoff flow
links, preempt/spill/resume marks under the global rid — plus
per-tick roofline attribution (``stats()['roofline']`` on every step
path, ``serving_step_mfu``/``serving_hbm_bw_util`` gauges), bounded
on-demand profiling windows (engine + cluster-forwarded), tracer
ring-drop accounting, the loadgen NDJSON record export, and the
``PADDLE_TPU_TRACE=0`` kill-switch bit-parity + zero-recompile pins."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.monitor import tracing as _tracing
from paddle_tpu.monitor.tracing import ProfilerWindow, Tracer
from paddle_tpu.inference import ServingConfig, ServingEngine
from paddle_tpu.inference.cluster import ClusterConfig, EngineCluster
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture
def llama_tiny():
    paddle.seed(7)
    cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                           kv_heads=2, ffn=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _prompts(rng, lens):
    return [rng.randint(1, 128, (n,)) for n in lens]


# ------------------------------------------------------------ tracer


def test_tracer_flow_events_chrome_schema():
    """Flow start/finish export as ph "s"/"f" with a shared top-level
    id (the Perfetto arrow contract), the finish binding to its
    enclosing slice (bp="e"); ids from next_flow_id are unique."""
    tr = Tracer("flows")
    with tr.span("exporter", tid=1):
        fid = _tracing.next_flow_id()
        tr.flow("kv handoff", tid=1, flow_id=fid, phase="s",
                args={"rid": 3})
    with tr.span("importer", tid=2):
        tr.flow("kv handoff", tid=2, flow_id=fid, phase="f",
                args={"rid": 3})
    evs = tr.chrome_events()
    s = [e for e in evs if e["ph"] == "s"]
    f = [e for e in evs if e["ph"] == "f"]
    assert len(s) == 1 and len(f) == 1
    assert s[0]["id"] == f[0]["id"] == fid
    assert f[0]["bp"] == "e"
    assert "flow_id" not in (s[0].get("args") or {})  # lifted to id
    assert s[0]["args"]["rid"] == 3
    assert "s" not in s[0] or s[0].get("s") != "t"  # not an instant
    assert _tracing.next_flow_id() > fid
    with pytest.raises(ValueError, match="phase"):
        tr.flow("x", phase="t")
    json.dumps(tr.chrome_trace())


def test_tracer_ring_drop_counter_metric():
    """The ring's silent truncation is now a metric: every overwrite
    bumps the process-wide trace_events_dropped counter AND the
    per-tracer dropped property (the observer observes itself)."""
    c = monitor.counter("trace_events_dropped")
    before = c.value()
    tr = Tracer("droppy", capacity=16)
    for i in range(50):
        tr.emit(f"e{i}")
    assert tr.dropped == 34
    assert c.value() - before == 34


def test_engine_stats_trace_events_dropped(llama_tiny, monkeypatch):
    """An engine whose ring wraps reports the loss in stats()
    (trace_events_dropped > 0); a roomy ring reports 0."""
    monkeypatch.setenv("PADDLE_TPU_TRACE_EVENTS", "32")
    rng = np.random.RandomState(3)
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        prefill_chunk=16))
    assert eng.tracer.capacity == 32
    eng.serve(_prompts(rng, (6, 20, 9, 14)), max_new_tokens=6)
    st = eng.stats()
    eng.shutdown()
    assert st["trace_events_dropped"] > 0
    assert st["trace_events"] == 32          # ring stayed bounded


# ---------------------------------------- merged cross-replica trace


def _disagg_cluster(model, rid_offset=0, **scfg):
    cl = EngineCluster(
        model, ClusterConfig(num_replicas=1, prefill_replicas=1),
        ServingConfig(num_slots=2, block_size=8, max_model_len=64,
                      prefill_chunk=16, **scfg))
    # skew the GLOBAL id namespace away from the replicas' local rid
    # counters so the rewrite is observable (locals start at 0 on
    # every engine; equal ids would vacuously "match")
    cl._next_rid += rid_offset
    return cl


def test_merged_disagg_trace_one_pid_per_replica(llama_tiny):
    """ONE merged Chrome trace from a disaggregated run: distinct
    pids per replica (+ the router lane), process names rewritten to
    replica<i>:<role>, router route spans carrying the global rid,
    handoff flow links resolving across pids, and one global
    request's spans visible on BOTH the prefill and decode pids —
    router -> prefill -> handoff -> decode under one rid."""
    rng = np.random.RandomState(5)
    cl = _disagg_cluster(llama_tiny, rid_offset=100)
    rids = [cl.submit(p, 4) for p in _prompts(rng, (6, 12, 9))]
    done = cl.run()
    assert sorted(done) == sorted(rids) and min(rids) >= 100
    doc = cl.export_trace()
    evs = doc["traceEvents"]
    json.dumps(doc)                                  # loadable
    # one pid per replica plus the cluster's own router lane
    procs = {e["pid"]: e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert len(procs) == 3
    names = set(procs.values())
    assert "replica0:decode" in names
    assert "replica1:prefill" in names
    assert "EngineCluster" in names
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)
    cluster_pid = next(p for p, n in procs.items()
                       if n == "EngineCluster")
    prefill_pid = next(p for p, n in procs.items()
                       if n == "replica1:prefill")
    decode_pid = next(p for p, n in procs.items()
                      if n == "replica0:decode")
    # router-decision spans: one per submit, global rid, on the
    # cluster lane
    routes = by_name["route"]
    assert len(routes) == len(rids)
    assert {e["args"]["rid"] for e in routes} == set(rids)
    assert all(e["pid"] == cluster_pid for e in routes)
    assert all(e["args"]["replica"] == 1 for e in routes)  # prefill
    placed = by_name["handoff placed"]
    assert {e["args"]["rid"] for e in placed} == set(rids)
    # handoff flow links: every start has exactly one finish with the
    # SAME id on a DIFFERENT pid (prefill -> decode), rid global
    starts = [e for e in evs if e["ph"] == "s"]
    finishes = {e["id"]: e for e in evs if e["ph"] == "f"}
    assert len(starts) == len(rids)
    for s in starts:
        f = finishes[s["id"]]
        assert s["pid"] == prefill_pid and f["pid"] == decode_pid
        assert s["args"]["rid"] == f["args"]["rid"]
        assert s["args"]["rid"] in rids
    # one request end-to-end: its rewritten req<gid> spans exist on
    # BOTH replica pids, and its per-tick spans carry the global rid
    g = rids[0]
    req_pids = {e["pid"] for e in evs
                if e["name"] == f"req{g}" and e["ph"] == "X"}
    assert req_pids == {prefill_pid, decode_pid}
    chunk = [e for e in by_name["prefill chunk"]
             if e["args"]["rid"] == g]
    assert chunk and all(e["pid"] == prefill_pid for e in chunk)
    dec = [e for e in by_name["decode tick"]
           if e["args"]["rid"] == g]
    assert dec and all(e["pid"] == decode_pid for e in dec)
    # no stale LOCAL ids survived in rid-carrying events of mapped
    # requests: every rid arg on replica pids is in the global range
    for e in evs:
        a = e.get("args") or {}
        if "rid" in a and e["pid"] != cluster_pid \
                and e["name"] != "submit":
            assert a["rid"] >= 100, e
    # cluster roofline headline: BOTH numbers from the ONE busiest
    # replica — never a per-metric max mixing replicas (which could
    # describe a utilization pair no replica exhibits); either
    # replica may win (the prefill tier's chunk rows ride its own
    # ragged tick executable), the invariant is the pairing
    st = cl.stats()
    roof = st["roofline"]
    rep = st["replicas"][roof["busiest_replica"]]["roofline"]
    assert roof["step_mfu"] == rep["step_mfu"] > 0
    assert roof["step_hbm_bw_util"] == rep["step_hbm_bw_util"] > 0
    cl.shutdown()


def test_rid_history_bounded_and_trace_gated(llama_tiny,
                                             monkeypatch):
    """The (replica, local rid) -> global rid rewrite history is
    FIFO-bounded (a rid older than every ring's reach can never need
    rewriting) and is NOT populated under the trace kill switch — a
    long-lived killed fleet accumulates nothing."""
    rng = np.random.RandomState(31)
    cl = EngineCluster(
        llama_tiny, ClusterConfig(num_replicas=1),
        ServingConfig(num_slots=2, block_size=8, max_model_len=64,
                      prefill_chunk=16))
    cl._hist_cap = 3
    for _ in range(3):
        cl.submit(rng.randint(1, 128, (6,)), 2)
        cl.run()
    for _ in range(2):
        cl.submit(rng.randint(1, 128, (6,)), 2)
        cl.run()
    assert len(cl._l2g_hist) == 3                 # pruned, oldest out
    assert set(cl._l2g_hist.values()) == {2, 3, 4}
    cl.shutdown()
    monkeypatch.setenv("PADDLE_TPU_TRACE", "0")
    cl0 = EngineCluster(
        llama_tiny, ClusterConfig(num_replicas=1),
        ServingConfig(num_slots=2, block_size=8, max_model_len=64,
                      prefill_chunk=16))
    cl0.submit(rng.randint(1, 128, (6,)), 2)
    cl0.run()
    assert cl0._l2g_hist == {}                    # dead weight gated
    cl0.shutdown()


def test_export_trace_writes_perfetto_file(llama_tiny, tmp_path):
    rng = np.random.RandomState(7)
    cl = _disagg_cluster(llama_tiny)
    cl.submit(rng.randint(1, 128, (9,)), 3)
    cl.run()
    p = cl.export_trace(str(tmp_path / "fleet.json"))
    doc = json.load(open(p))
    assert doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    cl.shutdown()


def test_preempt_spill_resume_spans_share_global_rid(llama_tiny):
    """A preempted request's preempt (spill) and resume marks land on
    its replica's lane with the CLUSTER-global rid after the rewrite
    — the merged timeline shows one request id across its whole
    preempted life (and the stream stays token-exact, pinned
    elsewhere; here we pin the trace schema)."""
    rng = np.random.RandomState(9)
    cl = EngineCluster(
        llama_tiny, ClusterConfig(num_replicas=1),
        ServingConfig(num_slots=2, block_size=8, max_model_len=96,
                      prefill_chunk=16))
    cl._next_rid += 500
    lo = cl.submit(rng.randint(1, 128, (20,)), 8, priority=0)
    for _ in range(3):
        cl.step()
    hi = [cl.submit(p, 6, priority=2)
          for p in _prompts(rng, (12, 9))]
    done = cl.run()
    assert sorted(done) == sorted([lo] + hi)
    st = cl.stats()
    assert st["preemptions"] >= 1
    evs = cl.export_trace()["traceEvents"]
    pre = [e for e in evs if e["name"] == "preempt"]
    res = [e for e in evs if e["name"] in ("resume", "resumed")]
    assert pre and res
    assert all(e["args"]["rid"] == lo for e in pre)
    assert any(e["args"]["rid"] == lo for e in res)
    # same pid (the victim's replica), global id — the spill/resume
    # pair joins against the request's other spans by rid
    assert {e["pid"] for e in pre} == {e["pid"] for e in res
                                       if e["args"]["rid"] == lo}
    cl.shutdown()


def test_trace_kill_switch_cluster_bit_parity(llama_tiny,
                                              monkeypatch):
    """PADDLE_TPU_TRACE=0 keeps the WHOLE recorder inert on a
    disaggregated cluster: identical tokens, identical executable
    counts (zero steady-state recompiles both ways), no tracers, no
    merged trace, profile() a refused no-op, drop accounting zero."""
    rng = np.random.RandomState(11)
    prompts = _prompts(rng, (6, 14, 9))

    def serve():
        cl = _disagg_cluster(llama_tiny)
        rids = [cl.submit(p.copy(), 5) for p in prompts]
        done = cl.run()
        rids2 = [cl.submit(p.copy(), 5) for p in prompts]
        done2 = cl.run()
        st = cl.stats()
        cl.shutdown()
        toks = [done[r].tolist() for r in rids] \
            + [done2[r].tolist() for r in rids2]
        return toks, st, cl

    on, st_on, _ = serve()
    monkeypatch.setenv("PADDLE_TPU_TRACE", "0")
    off, st_off, cl_off = serve()
    assert on == off, "trace kill switch changed served tokens"
    assert st_on["tracing"] is True
    assert st_off["tracing"] is False
    assert st_off["trace_events_dropped"] == 0
    assert st_off["profile_captures"] == 0
    # same executables, second wave compiled nothing, either way
    assert st_off["executables_compiled"] == \
        st_on["executables_compiled"]
    assert cl_off.export_trace() is None
    assert cl_off.profile(2, "/tmp/never") is None
    for rep in st_off["replicas"]:
        assert rep["tracing"] is False
        assert rep["trace_events_dropped"] == 0


# ----------------------------------------------------------- roofline


def test_roofline_stats_ragged_engine(llama_tiny):
    """The default (ragged) engine reports per-executable MFU +
    HBM-bandwidth utilization fused from the XLA cost model and the
    measured tick time, with a bound classification against the
    chip's ridge point; cpu_proxy flags the nominal peaks here."""
    rng = np.random.RandomState(13)
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        prefill_chunk=16))
    roof0 = eng.stats()["roofline"]
    assert roof0["step_mfu"] == 0.0 and roof0["per_executable"] == {}
    eng.serve(_prompts(rng, (6, 14, 9)), max_new_tokens=5)
    roof = eng.stats()["roofline"]
    eng.shutdown()
    assert roof["cpu_proxy"] is True            # tier-1 runs on CPU
    assert roof["tick_executable"] == "decode"
    assert roof["step_mfu"] > 0.0
    assert roof["step_hbm_bw_util"] > 0.0
    assert roof["ridge_flops_per_byte"] == pytest.approx(
        roof["peak_flops_per_s"] / roof["peak_hbm_bytes_per_s"])
    row = roof["per_executable"]["decode"]
    assert row["flops"] > 0 and row["bytes_accessed"] > 0
    assert row["arithmetic_intensity"] == pytest.approx(
        row["flops"] / row["bytes_accessed"], rel=1e-3)
    assert row["bound"] in ("compute", "bandwidth")
    assert row["bound"] == ("compute" if row["arithmetic_intensity"]
                            >= roof["ridge_flops_per_byte"]
                            else "bandwidth")
    assert row["ticks"] > 0 and row["step_time_ms"] > 0
    assert row["mfu"] == pytest.approx(
        row["flops"] / (row["step_time_ms"] / 1000.0)
        / roof["peak_flops_per_s"], rel=0.05)
    # the headline gauges track the tick executable
    assert monitor.gauge("serving_step_mfu").value() > 0.0
    assert monitor.gauge("serving_hbm_bw_util").value() > 0.0


def test_roofline_stats_legacy_and_spec_paths(llama_tiny):
    """The legacy per-width path attributes decode ticks AND chunk
    prefills; a speculative engine attributes its verify tick — the
    roofline block covers every step path, not just the default."""
    rng = np.random.RandomState(17)
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        prefill_chunk=16, ragged_batch=False))
    eng.serve(_prompts(rng, (6, 20)), max_new_tokens=4)
    roof = eng.stats()["roofline"]
    eng.shutdown()
    assert roof["per_executable"]["decode"]["mfu"] > 0
    assert roof["per_executable"]["chunk"]["ticks"] > 0
    assert roof["per_executable"]["chunk"]["flops"] > 0

    phrase = rng.randint(1, 128, (6,))
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        prefill_chunk=16, num_speculative_tokens=2))
    eng.serve([np.tile(phrase, 4), np.tile(phrase, 3)],
              max_new_tokens=5)
    roof = eng.stats()["roofline"]
    eng.shutdown()
    assert roof["tick_executable"] == "verify"
    assert roof["step_mfu"] > 0.0
    assert roof["per_executable"]["verify"]["hbm_bw_util"] > 0.0


def test_roofline_accounting_compiles_nothing(llama_tiny):
    """The roofline fuses ALREADY-compiled executables' cost analyses
    with host timestamps: two waves stay at one executable, zero
    steady-state recompiles (the whole recorder is host-side)."""
    rng = np.random.RandomState(19)
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        prefill_chunk=16))
    eng.serve(_prompts(rng, (6, 9)), max_new_tokens=4)
    st1 = eng.stats()
    eng.serve(_prompts(rng, (7, 11)), max_new_tokens=4)
    st2 = eng.stats()
    eng.shutdown()
    assert st1["executables_compiled"] == 1
    assert st2["executables_compiled"] == 1
    assert st2["roofline"]["per_executable"]["decode"]["ticks"] \
        > st1["roofline"]["per_executable"]["decode"]["ticks"]


# ------------------------------------------------- profiling windows


def test_profiler_window_mechanics(monkeypatch, tmp_path):
    """Window lifecycle with injected hooks: start fires once before
    the first armed tick, stop after the Nth, captures count; arming
    twice raises; no dir raises; PADDLE_TPU_PROFILE_DIR supplies the
    default; the PADDLE_TPU_TRACE=0 kill switch refuses."""
    calls = []
    w = ProfilerWindow(start=lambda d: calls.append(("start", d)),
                       stop=lambda: calls.append(("stop",)))
    with pytest.raises(ValueError, match="dir"):
        w.arm(2)
    assert w.arm(2, str(tmp_path)) == str(tmp_path)
    with pytest.raises(RuntimeError, match="already"):
        w.arm(1, str(tmp_path))
    with pytest.raises(ValueError, match="n_ticks"):
        ProfilerWindow().arm(0, str(tmp_path))
    assert w.pending == 2
    for _ in range(2):
        w.tick_begin()
        w.tick_end()
    assert calls == [("start", str(tmp_path)), ("stop",)]
    assert w.pending == 0 and w.captures == 1
    assert w.last_dir == str(tmp_path)
    w.tick_begin()                      # idle: no-ops
    w.tick_end()
    assert calls == [("start", str(tmp_path)), ("stop",)]
    monkeypatch.setenv("PADDLE_TPU_PROFILE_DIR", str(tmp_path / "e"))
    w2 = ProfilerWindow(start=lambda d: calls.append(("start", d)),
                        stop=lambda: calls.append(("stop",)))
    assert w2.arm(1) == str(tmp_path / "e")     # env default
    # a failing stop disarms but is NOT a completed capture (the
    # captures counter only reports profiles actually written)
    w3 = ProfilerWindow(start=lambda d: None,
                        stop=lambda: (_ for _ in ()).throw(
                            RuntimeError("disk full")))
    w3.arm(1, str(tmp_path))
    w3.tick_begin()
    with pytest.warns(UserWarning, match="stop failed"):
        w3.tick_end()
    assert w3.captures == 0 and w3.pending == 0
    assert w3.last_dir is None
    assert w3.arm(1, str(tmp_path))             # re-armable after
    monkeypatch.setenv("PADDLE_TPU_TRACE", "0")
    assert ProfilerWindow().arm(3, str(tmp_path)) is None


def test_engine_and_cluster_profile_windows(llama_tiny, tmp_path):
    """engine.profile(n) brackets exactly the next n engine ticks;
    EngineCluster.profile(n) brackets n CLUSTER ticks (one process-
    wide capture covering every replica); stats() reports the
    completed captures."""
    rng = np.random.RandomState(23)
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        prefill_chunk=16))
    calls = []
    eng._prof = ProfilerWindow(
        start=lambda d: calls.append(("start", d)),
        stop=lambda: calls.append(("stop",)))
    eng.submit(rng.randint(1, 128, (6,)), 6)
    assert eng.profile(2, str(tmp_path / "p")) == str(tmp_path / "p")
    assert eng.stats()["profile_ticks_remaining"] == 2
    eng.step()
    assert calls == [("start", str(tmp_path / "p"))]
    eng.run()
    st = eng.stats()
    eng.shutdown()
    assert calls == [("start", str(tmp_path / "p")), ("stop",)]
    assert st["profile_captures"] == 1
    assert st["profile_ticks_remaining"] == 0

    cl = _disagg_cluster(llama_tiny)
    ccalls = []
    cl._prof = ProfilerWindow(
        start=lambda d: ccalls.append(("start", d)),
        stop=lambda: ccalls.append(("stop",)))
    cl.submit(rng.randint(1, 128, (9,)), 4)
    cl.profile(3, str(tmp_path / "c"))
    cl.run()
    st = cl.stats()
    cl.shutdown()
    assert ccalls == [("start", str(tmp_path / "c")), ("stop",)]
    assert st["profile_captures"] == 1


# ------------------------------------------------- loadgen NDJSON


def test_loadgen_record_export_joins_cluster(llama_tiny, tmp_path):
    """run(record_path=) writes one NDJSON row per request — submit /
    first-token / last-token monotonic timestamps, priority, outcome,
    and the ROUTED replica id (cluster targets) — so offline analysis
    joins load-gen records against the merged trace."""
    from paddle_tpu.inference.loadgen import run_load
    rng = np.random.RandomState(29)
    cl = EngineCluster(
        llama_tiny, ClusterConfig(num_replicas=2),
        ServingConfig(num_slots=2, block_size=8, max_model_len=64,
                      prefill_chunk=16))
    prompts = _prompts(rng, (6, 9, 12, 7))
    path = str(tmp_path / "records.ndjson")
    rep = run_load(cl, prompts, mode="closed", concurrency=2,
                   max_new_tokens=4, priorities=[0, 1, 0, 1],
                   record_path=path)
    cl.shutdown()
    assert rep["record_path"] == path
    rows = [json.loads(ln) for ln in open(path)]
    assert len(rows) == len(prompts)
    assert [r["rid"] for r in rows] == sorted(r["rid"] for r in rows)
    for r in rows:
        assert r["outcome"] == "completed"
        assert r["replica"] in (0, 1)
        assert r["priority"] in (0, 1)
        assert r["submit_t_s"] <= r["first_token_t_s"] \
            <= r["last_token_t_s"]
        assert r["n_tokens"] == 4
        assert r["ttft_ms"] >= 0 and r["e2e_ms"] >= r["ttft_ms"]
    # plain engine target: replica is null (no router in the path)
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        prefill_chunk=16))
    path2 = str(tmp_path / "engine.ndjson")
    run_load(eng, prompts[:2], mode="closed", concurrency=2,
             max_new_tokens=3, record_path=path2)
    eng.shutdown()
    rows = [json.loads(ln) for ln in open(path2)]
    assert len(rows) == 2
    assert all(r["replica"] is None for r in rows)


# ------------------------------------------------------------- guard


def test_tier1_no_slow_marker():
    """CI guard (the PR-4/5 pattern): every flight-recorder test runs
    in the tier-1 ``-m 'not slow'`` sweep, the merged-trace schema
    test is present, and engines/clusters tear down through the
    leak-sweeping ``shutdown()``."""
    import tests.conftest as c
    here = open(__file__).read()
    assert "pytest.mark.slow" not in here.replace(
        '"pytest.mark.slow"', "")
    names = [ln.split("(")[0][4:] for ln in here.splitlines()
             if ln.startswith("def test_")]
    overlap = set(names) & set(c._SLOW_TESTS)
    assert not overlap, \
        f"tier-1 flight-recorder tests marked slow: {overlap}"
    assert "test_merged_disagg_trace_one_pid_per_replica" in names
    assert "test_trace_kill_switch_cluster_bit_parity" in names
    assert here.count(".shutdown()") >= 10, \
        "shutdown (leak sweep) must guard these tests"
