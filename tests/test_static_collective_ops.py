"""Static-graph collective operators (reference:
``paddle/fluid/operators/collective/c_*_op.cc``): a static ``Program``
built op-by-op with explicit comm nodes must record and EXECUTE them —
the r4 verdict's missing row #6."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static
import paddle_tpu.distributed as dist


def test_program_records_and_executes_collective_nodes():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 8], "float32")
        y = x * 2.0
        r = dist.c_allreduce_sum(y)          # explicit comm node
        z = dist.c_identity(r) + 1.0
        out = dist.c_sync_comm_stream(z)
    exe = static.Executor()
    feed = {"x": np.ones((4, 8), np.float32)}
    res = exe.run(main, feed=feed, fetch_list=[out])[0]
    # single-process group: allreduce over one rank is identity
    np.testing.assert_allclose(np.asarray(res), 2.0 * np.ones((4, 8))
                               + 1.0)


def test_c_ops_eager_verbs():
    t = paddle.to_tensor(np.arange(8, dtype=np.float32))
    r = dist.c_allreduce_sum(t)
    np.testing.assert_allclose(r.numpy(), t.numpy())
    m = dist.c_allreduce_max(t)
    np.testing.assert_allclose(m.numpy(), t.numpy())
    b = dist.c_broadcast(t, root=0)
    np.testing.assert_allclose(b.numpy(), t.numpy())
    i = dist.c_identity(t)
    np.testing.assert_allclose(i.numpy(), t.numpy())
    rs = dist.c_reducescatter(t)
    assert rs is not None
    red = dist.reduce(paddle.to_tensor(np.ones(4, np.float32)), dst=0)
    np.testing.assert_allclose(red.numpy(), np.ones(4))


def test_c_split_and_concat_roundtrip():
    t = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(2, 8))
    piece = dist.c_split(t, rank=0, nranks=2)
    assert tuple(piece.shape) == (2, 4)
    np.testing.assert_allclose(piece.numpy(), t.numpy()[:, :4])
