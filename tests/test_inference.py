"""Inference stack: jit.save exports an AOT StableHLO module; the
Config/Predictor API (AnalysisPredictor parity) runs it and matches
eager outputs."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import InputSpec


def _make_mlp():
    paddle.seed(11)
    return paddle.nn.Sequential(
        paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
        paddle.nn.Linear(32, 4))


def test_jit_save_load_roundtrip(tmp_path):
    model = _make_mlp()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(3, 16).astype(np.float32))
    model.eval()
    expected = model(x).numpy()

    path = str(tmp_path / "mlp")
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([3, 16], "float32", "x")])
    assert os.path.exists(path + ".pdmodel")
    assert os.path.exists(path + ".pdparams")

    loaded = paddle.jit.load(path)
    got = loaded(x).numpy()
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_jit_load_state_dict_only(tmp_path):
    model = _make_mlp()
    path = str(tmp_path / "params_only")
    paddle.jit.save(model, path)  # no input_spec -> params only
    loaded = paddle.jit.load(path)
    assert set(loaded.state_dict().keys()) == set(
        model.state_dict().keys())
    with pytest.raises(RuntimeError):
        loaded(paddle.to_tensor(np.zeros((3, 16), np.float32)))


def test_predictor_named_handles(tmp_path):
    model = _make_mlp()
    x = np.random.RandomState(1).randn(3, 16).astype(np.float32)
    model.eval()
    expected = model(paddle.to_tensor(x)).numpy()

    path = str(tmp_path / "deploy")
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([3, 16], "float32", "x")])

    from paddle_tpu import inference
    config = inference.Config(path)
    predictor = inference.create_predictor(config)

    assert predictor.get_input_names() == ["x"]
    h = predictor.get_input_handle("x")
    h.copy_from_cpu(x)
    assert predictor.run()
    out_name = predictor.get_output_names()[0]
    out = predictor.get_output_handle(out_name).copy_to_cpu()
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)


def test_predictor_run_list_api(tmp_path):
    model = _make_mlp()
    x = np.random.RandomState(2).randn(3, 16).astype(np.float32)
    model.eval()
    expected = model(paddle.to_tensor(x)).numpy()

    path = str(tmp_path / "deploy2")
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([3, 16], "float32", "x")])
    from paddle_tpu import inference
    predictor = inference.create_predictor(inference.Config(path))
    outs = predictor.run([x])
    np.testing.assert_allclose(outs[0], expected, rtol=1e-5, atol=1e-6)


def test_exported_artifact_survives_fresh_weights(tmp_path):
    """The .pdmodel captures the program; .pdparams carries weights —
    the predictor must compute with SAVED weights, not live ones."""
    model = _make_mlp()
    x = paddle.to_tensor(
        np.random.RandomState(3).randn(2, 16).astype(np.float32))
    model.eval()
    expected = model(x).numpy()
    path = str(tmp_path / "frozen")
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([2, 16], "float32", "x")])
    # mutate live weights after save
    for p in model.parameters():
        p.set_value(np.zeros(p.shape, np.float32))
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(loaded(x).numpy(), expected,
                               rtol=1e-5, atol=1e-6)


def test_lenet_export(tmp_path):
    from paddle_tpu.vision.models import LeNet
    paddle.seed(0)
    model = LeNet()
    model.eval()
    x = np.random.RandomState(0).randn(2, 1, 28, 28).astype(np.float32)
    expected = model(paddle.to_tensor(x)).numpy()
    path = str(tmp_path / "lenet")
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([2, 1, 28, 28], "float32",
                                          "image")])
    from paddle_tpu import inference
    predictor = inference.create_predictor(inference.Config(path))
    outs = predictor.run([x])
    np.testing.assert_allclose(outs[0], expected, rtol=1e-4, atol=1e-5)


def test_jit_save_dynamic_batch_spec(tmp_path):
    """InputSpec([None, d]) exports a symbolic-batch module: every batch
    size must work at load time (not just 1)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec
    paddle.seed(0)
    layer = nn.Linear(6, 3)
    path = str(tmp_path / "dynmodel")
    paddle.jit.save(layer, path,
                    input_spec=[InputSpec([None, 6], "float32")])
    loaded = paddle.jit.load(path)
    for b in (1, 4, 9):
        x = np.random.RandomState(b).randn(b, 6).astype(np.float32)
        ref = layer(paddle.to_tensor(x)).numpy()
        out = loaded(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(out.numpy()), ref,
                                   rtol=1e-5, atol=1e-6)


def test_jit_save_dynamic_batch_two_inputs(tmp_path):
    """Two dynamic-batch inputs must share one batch symbol (x + y)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec

    class AddNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x, y):
            return self.fc(x) + y

    paddle.seed(1)
    net = AddNet()
    path = str(tmp_path / "dyn2")
    paddle.jit.save(net, path, input_spec=[
        InputSpec([None, 4], "float32"), InputSpec([None, 4], "float32")])
    loaded = paddle.jit.load(path)
    x = np.random.RandomState(0).randn(5, 4).astype(np.float32)
    y = np.random.RandomState(1).randn(5, 4).astype(np.float32)
    ref = net(paddle.to_tensor(x), paddle.to_tensor(y)).numpy()
    out = loaded(paddle.to_tensor(x), paddle.to_tensor(y))
    np.testing.assert_allclose(np.asarray(out.numpy()), ref, rtol=1e-5,
                               atol=1e-6)
