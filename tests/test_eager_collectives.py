"""Multi-process EAGER collectives (reference TestDistBase pattern —
``test/legacy_test/test_dist_base.py``: the driver spawns real worker
processes; collectives cross process boundaries, not shard_map axes).
Round-2 verdict item 6: eager facades must stop being identity in a
multi-process world."""
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn(world, mode, tmpdir):
    port = _free_port()
    endpoints = ",".join(f"127.0.0.1:{6170 + i}" for i in range(world))
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT": f"127.0.0.1:{6170 + rank}",
            "PADDLE_EAGER_STORE": f"127.0.0.1:{port}",
            "JAX_PLATFORMS": "cpu",
            "PYTHONFAULTHANDLER": "1",
            # repo only: inheriting the axon sitecustomize would route
            # "cpu" compiles to the TPU emulation, whose f32 rounding
            # differs from the driver's real-CPU math
            "PYTHONPATH": os.getcwd(),
        })
        for k in ("PADDLE_MASTER", "PALLAS_AXON_POOL_IPS",
                  "PALLAS_AXON_REMOTE_COMPILE"):
            env.pop(k, None)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join("tests", "dist_worker.py"),
             mode, str(tmpdir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    deadline = time.time() + 240
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(deadline - time.time(), 5))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out.decode(errors="replace"))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    results = {}
    for rank in range(world):
        with open(os.path.join(str(tmpdir), f"rank{rank}.json")) as f:
            results[rank] = json.load(f)
    return results


@pytest.mark.parametrize("world", [2, 4])
def test_cross_process_collectives(world, tmp_path):
    res = _spawn(world, "collectives", tmp_path)
    expect_sum = [float(sum(range(1, world + 1)))] * 4
    for rank in range(world):
        r = res[rank]
        assert r["allreduce_sum"] == expect_sum
        assert r["allgather"] == [[float(i)] * 2 for i in range(world)]
        assert r["broadcast"] == [15.0]          # src rank 1: 1*10+5
        # reduce_scatter of (arange(world*2) + rank) summed over ranks
        base = np.arange(world * 2, dtype=np.float64)
        full = base * world + sum(range(world))
        chunk = full[rank * 2:(rank + 1) * 2]
        assert r["reduce_scatter"] == chunk.tolist()
        # alltoall: out[d] = chunk destined to me from rank d
        assert r["alltoall"] == [[d * 100.0 + rank]
                                 for d in range(world)]
    assert res[1]["recv"] == [123.0]


def test_dataparallel_loss_parity_vs_single_process(tmp_path):
    world = 2
    res = _spawn(world, "dp", tmp_path)
    # workers all-reduce their shard losses -> identical on every rank
    assert res[0]["losses"] == res[1]["losses"]

    # single-process reference on the FULL batch, same seed/model/lr
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    paddle.seed(42)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    rng = np.random.RandomState(0)
    X = rng.randn(8, 4).astype(np.float32)
    Y = rng.randn(8, 1).astype(np.float32)
    ref = []
    for _ in range(4):
        out = net(paddle.to_tensor(X))
        loss = ((out - paddle.to_tensor(Y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        ref.append(float(loss.numpy()))
    np.testing.assert_allclose(res[0]["losses"], ref, rtol=1e-5,
                               atol=1e-6)
