"""paddle.incubate.autograd — functional jvp/vjp/Jacobian/Hessian
(reference: ``python/paddle/incubate/autograd/``; round-2 verdict
missing item 5's second half)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_vjp_matches_backward():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32),
                         stop_gradient=False)
    from paddle_tpu.incubate import autograd as iag
    out, g = iag.vjp(lambda t: (t ** 2).sum(), x)
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0, 6.0])
    np.testing.assert_allclose(float(out.numpy()), 14.0)


def test_jvp_forward_mode():
    from paddle_tpu.incubate import autograd as iag
    x = paddle.to_tensor(np.array([2.0], np.float32))
    v = paddle.to_tensor(np.array([1.0], np.float32))
    out, tangent = iag.jvp(lambda t: t ** 3, x, v)
    np.testing.assert_allclose(tangent.numpy(), [12.0])  # 3x^2


def test_jacobian_dense():
    from paddle_tpu.incubate import autograd as iag
    rng = np.random.RandomState(0)
    A = rng.randn(3, 4).astype(np.float32)
    x = paddle.to_tensor(rng.randn(4).astype(np.float32))
    J = iag.Jacobian(lambda t: paddle.matmul(
        paddle.to_tensor(A), t), x)
    np.testing.assert_allclose(J[:].numpy(), A, rtol=1e-5)
    np.testing.assert_allclose(J[1].numpy(), A[1], rtol=1e-5)


def test_hessian_quadratic():
    from paddle_tpu.incubate import autograd as iag
    rng = np.random.RandomState(1)
    Q = rng.randn(3, 3).astype(np.float32)
    Q = (Q + Q.T) / 2
    x = paddle.to_tensor(rng.randn(3).astype(np.float32))

    def f(t):
        return 0.5 * paddle.matmul(
            t, paddle.matmul(paddle.to_tensor(Q), t))

    H = iag.Hessian(f, x)
    np.testing.assert_allclose(H[:].numpy(), Q, rtol=1e-4, atol=1e-5)


def test_autograd_hessian_api_works_now():
    """paddle.autograd.hessian routes through the functional path."""
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    H = paddle.autograd.hessian(lambda t: (t ** 3).sum(), x)
    want = np.diag([6.0, 12.0])
    np.testing.assert_allclose(
        H[:].numpy() if hasattr(H, "__getitem__") else H.numpy(),
        want, rtol=1e-5)
