"""Prefix-cached paged KV + chunked prefill (ISSUE 5): content-hashed
block reuse (refcounts, hash->block index, LRU eviction), copy-on-write
on shared-block appends, the ONE fixed-chunk prefill executable
(zero steady-state prefill recompiles), greedy token exactness with
prefix caching ON vs OFF (Llama / GPT / int8 / speculative), the
chunk-attention kernel in interpret mode, both kill switches
(``PADDLE_TPU_PREFIX_CACHE=0`` / ``PADDLE_TPU_CHUNKED_PREFILL=0``),
and ``BlockAllocator.check_leaks`` at engine shutdown.

Tier-1 guard: every test here must run in the standard
``-m 'not slow'`` sweep — ``test_tier1_no_slow_marker`` pins that.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.inference import ServingConfig, ServingEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture
def llama_tiny():
    paddle.seed(7)
    cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                           kv_heads=2, ffn=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _mk_engine(model, **kw):
    base = dict(num_slots=2, block_size=8, max_model_len=96,
                prefill_chunk=8, min_prefill_bucket=8)
    base.update(kw)
    return ServingEngine(model, ServingConfig(**base))


def _shared_prefix_prompts(rng, vocab=128, prefix_len=24,
                           tails=(5, 9, 3)):
    sysp = rng.randint(1, vocab, (prefix_len,))
    return [np.concatenate([sysp, rng.randint(1, vocab, (t,))])
            for t in tails]


# ----------------------------------------------------------- allocator
# refcount / hash-index / LRU invariants


def test_allocator_refcount_publish_lru_property():
    """Random interleaving of alloc / ref / free / publish never leaks
    a block, never frees a block with live references, and keeps the
    free + cached + referenced partition exact (check_leaks passes at
    every quiescent point)."""
    from paddle_tpu.ops.paged_cache import BlockAllocator, chain_hashes
    rng = np.random.RandomState(0)
    a = BlockAllocator(17)                  # blocks 1..16
    live = {}                               # block -> our refcount
    published = {}                          # hash -> block
    next_tag = [0]

    def fresh_hash():
        next_tag[0] += 1
        return chain_hashes(b"prop", [next_tag[0]] * 4, 4)[0]

    for _ in range(400):
        op = rng.randint(4)
        if op == 0 and a.free_blocks:       # alloc 1..3
            n = min(1 + rng.randint(3), a.free_blocks)
            for b in a.alloc(n):
                live[b] = live.get(b, 0) + 1
        elif op == 1 and live:              # free one reference
            b = list(live)[rng.randint(len(live))]
            a.free([b])
            live[b] -= 1
            if not live[b]:
                del live[b]
        elif op == 2 and live:              # publish a live block
            b = list(live)[rng.randint(len(live))]
            h = fresh_hash()
            if a.publish(b, h):
                published[h] = b
        elif op == 3 and published:         # lookup + ref a cached one
            h = list(published)[rng.randint(len(published))]
            b = a.lookup(h)
            if b is not None:
                a.ref(b)
                live[b] = live.get(b, 0) + 1
        # prune published entries the LRU has evicted
        published = {h: b for h, b in published.items()
                     if a.lookup(h) == b}
        a.check_leaks(live)
    # over-freeing must be rejected while references are consistent
    if live:
        b = next(iter(live))
        a.free([b] * live.pop(b))
        with pytest.raises(ValueError, match="double free"):
            a.free([b])


def test_allocator_eviction_is_lru_ordered():
    from paddle_tpu.ops.paged_cache import BlockAllocator
    a = BlockAllocator(5)                   # 4 usable
    blocks = a.alloc(4)
    for i, b in enumerate(blocks):
        a.publish(b, bytes([i]))
    # free in a known order -> cache order b0, b1, b2, b3 (b0 oldest)
    for b in blocks:
        a.free([b])
    assert a.cached_blocks == 4 and a.free_blocks == 4
    got = a.alloc(2)                        # evicts the two oldest
    assert a.evictions == 2
    assert a.lookup(bytes([0])) is None
    assert a.lookup(bytes([1])) is None
    assert a.lookup(bytes([2])) == blocks[2]
    assert a.lookup(bytes([3])) == blocks[3]
    assert sorted(got) == sorted(blocks[:2])


def test_chain_hashes_prefix_sensitivity():
    """Equal hashes must imply equal prefixes THROUGH the block: a
    change anywhere earlier changes every later hash (and the seed
    partitions models)."""
    from paddle_tpu.ops.paged_cache import chain_hashes
    toks = list(range(40))
    h = chain_hashes(b"m1", toks, 8)
    assert len(h) == 5                      # full blocks only
    assert chain_hashes(b"m1", toks[:17], 8) == h[:2]
    mut = list(toks)
    mut[3] += 1                             # early mutation
    h2 = chain_hashes(b"m1", mut, 8)
    assert all(x != y for x, y in zip(h, h2))
    assert chain_hashes(b"m2", toks, 8)[0] != h[0]


def test_write_tokens_overflow_routes_to_null_block():
    """Chunk-prefill pad positions past the table's reach must land in
    the null block, NOT clamp onto the slot's last real block."""
    import jax.numpy as jnp
    from paddle_tpu.ops import paged_cache as pc
    rng = np.random.RandomState(3)
    S, T, H, D, BS, MB = 1, 6, 2, 4, 4, 2
    kp, vp = pc.init_pool(1 + MB, BS, H, D, jnp.float32)
    tables = jnp.asarray([[1, 2]], jnp.int32)
    k = jnp.asarray(rng.randn(S, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(S, T, H, D), jnp.float32)
    # write starts at position 5: tokens land at 5..10 but the table
    # only covers 8 positions — 6..7 in-table, 8..10 overflow
    kp2, _ = pc.write_tokens(kp, vp, tables, jnp.asarray([5]), k, v)
    dense = np.asarray(pc.gather_dense(kp2, tables))[0]
    np.testing.assert_array_equal(dense[5], np.asarray(k[0, 0]))
    np.testing.assert_array_equal(dense[7], np.asarray(k[0, 2]))
    # block 1 position 0..1 (cache positions 4 and the like) untouched
    assert not dense[:5].any()
    # the overflow went to block 0 (null), never to blocks 1/2
    assert np.asarray(kp2)[0].any()


# ------------------------------------------------- engine-level reuse +
# copy-on-write + eviction


def test_prefix_reuse_and_exactness_shared_system_prompt(llama_tiny):
    """The headline behavior: requests sharing a system prompt reuse
    its blocks (hit rate > 0, suffix-only prefill) and the greedy
    tokens are EXACTLY the cold-cache outputs."""
    rng = np.random.RandomState(0)
    prompts = _shared_prefix_prompts(rng)
    cold = _mk_engine(llama_tiny, enable_prefix_cache=False)
    want = cold.serve(list(prompts), max_new_tokens=6)
    want += cold.serve(list(prompts), max_new_tokens=6)
    cold.shutdown()
    assert cold.stats()["prefix_tokens_reused"] == 0

    eng = _mk_engine(llama_tiny)
    got = eng.serve(list(prompts), max_new_tokens=6)
    got += eng.serve(list(prompts), max_new_tokens=6)
    st = eng.stats()
    eng.shutdown()
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)
    assert st["prefix_blocks_reused"] > 0
    assert st["prefix_tokens_reused"] > 0
    assert 0.0 < st["prefix_hit_rate"] < 1.0
    assert st["cached_blocks"] > 0
    # one engine, ONE executable total — the ragged step subsumed the
    # prefill path entirely (no separate chunk exec, no bucket zoo)
    assert st["executables_compiled"] == 1
    assert st["prefill_compiles"] == 0


def test_cow_never_mutates_shared_block(llama_tiny):
    """A full-prompt hit appends the recomputed last token into a
    SHARED block: the engine must COW-duplicate it — the published
    block's bytes are identical before and after the reusing
    request."""
    import jax.numpy as jnp
    from paddle_tpu.ops import paged_cache as pc
    rng = np.random.RandomState(5)
    prompt = rng.randint(1, 128, (16,))     # exact block multiple
    eng = _mk_engine(llama_tiny, num_slots=1)
    (r1,) = eng.serve([prompt], max_new_tokens=4)
    assert eng.stats()["cow_copies"] == 0
    # the prompt's two full blocks are now published + cached
    hashes = pc.chain_hashes(eng._fp, prompt, eng._bs)
    shared = [eng._alloc.lookup(h) for h in hashes]
    assert all(b is not None for b in shared)
    before = [np.asarray(eng._pools[0][0][b]).copy() for b in shared]
    (r2,) = eng.serve([prompt], max_new_tokens=4)
    st = eng.stats()
    eng.shutdown()
    np.testing.assert_array_equal(r1, r2)
    assert st["cow_copies"] >= 1, "full-prompt hit must COW"
    after = [np.asarray(eng._pools[0][0][b]) for b in shared]
    for b, x, y in zip(shared, before, after):
        np.testing.assert_array_equal(x, y), f"shared block {b} mutated"


def test_eviction_under_pressure_admission_succeeds(llama_tiny):
    """A pool too small to hold the cache + a new request must evict
    LRU cached blocks transparently — admission never fails and the
    drained pool accounts for every block."""
    rng = np.random.RandomState(6)
    eng = _mk_engine(llama_tiny, num_slots=1, max_model_len=48,
                     num_blocks=9)
    for _ in range(6):                       # distinct prompts: the
        eng.serve([rng.randint(1, 128, (17,))],  # cache fills + churns
                  max_new_tokens=4)
    st = eng.stats()
    eng.shutdown()                           # check_leaks inside
    assert st["cache_evictions"] > 0, "pressure must evict"
    assert st["requests_completed"] == 6
    assert st["free_blocks"] == 8            # free + cached, no leaks
    assert st["reserved_blocks"] == 0


def test_scheduler_property_with_prefix_cache(llama_tiny):
    """The PR-3 scheduler property, now with shared prefixes + block
    sharing in play: every request completes exactly once under slot +
    block pressure, streamed == returned, allocator drains clean."""
    rng = np.random.RandomState(1)
    sysp = rng.randint(1, 128, (16,))
    streamed = {}
    eng = ServingEngine(
        llama_tiny,
        ServingConfig(num_slots=2, block_size=8, max_model_len=48,
                      num_blocks=15, prefill_chunk=8),
        stream_callback=lambda rid, t: streamed.setdefault(rid, [])
        .append(t))
    rids, news = [], [4, 7, 1, 5, 3, 8, 2, 6]
    for n, mn in zip([3, 18, 6, 17, 20, 2, 19, 5], news):
        p = np.concatenate([sysp, rng.randint(1, 128, (n,))]) \
            if n >= 16 else rng.randint(1, 128, (n,))
        rids.append(eng.submit(p, mn))
    done = eng.run()
    st = eng.stats()
    eng.shutdown()
    assert sorted(done) == sorted(rids)
    for rid, mn in zip(rids, news):
        assert 1 <= len(done[rid]) <= mn
        assert streamed[rid] == list(done[rid])
    assert st["active"] == 0 and st["queued"] == 0
    assert st["reserved_blocks"] == 0
    assert st["free_blocks"] == 14, "block leak (free + cached)"


# --------------------------------------------- exactness across models,
# speculative decoding, and the interleaved scheduler


def test_prefix_exactness_gpt():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(3)
    m = GPTForCausalLM(GPTConfig.tiny(vocab=96, hidden=64, layers=2,
                                      heads=4))
    m.eval()
    rng = np.random.RandomState(2)
    prompts = _shared_prefix_prompts(rng, vocab=96)
    cold = _mk_engine(m, enable_prefix_cache=False)
    want = cold.serve(list(prompts), max_new_tokens=4)
    want += cold.serve(list(prompts), max_new_tokens=4)
    eng = _mk_engine(m)
    got = eng.serve(list(prompts), max_new_tokens=4)
    got += eng.serve(list(prompts), max_new_tokens=4)
    st = eng.stats()
    eng.shutdown()
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)
    assert st["prefix_tokens_reused"] > 0


def test_prefix_exactness_int8():
    from paddle_tpu.nn.quant import quantize_for_inference
    paddle.seed(11)
    cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                           kv_heads=2, ffn=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    quantize_for_inference(m)
    rng = np.random.RandomState(9)
    prompts = _shared_prefix_prompts(rng)
    cold = _mk_engine(m, enable_prefix_cache=False)
    want = cold.serve(list(prompts), max_new_tokens=4)
    want += cold.serve(list(prompts), max_new_tokens=4)
    eng = _mk_engine(m)
    got = eng.serve(list(prompts), max_new_tokens=4)
    got += eng.serve(list(prompts), max_new_tokens=4)
    st = eng.stats()
    eng.shutdown()
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)
    assert st["prefix_tokens_reused"] > 0


def test_prefix_exactness_with_speculative(llama_tiny):
    """Shared prefix + the speculative verify/rollback machinery: the
    greedy stream must match prefix caching OFF token for token, while
    blocks are actually being reused (the rollback-garbage-vs-publish
    interplay: only positions < cache_len are ever hashed)."""
    rng = np.random.RandomState(4)
    pattern = rng.randint(1, 128, (8,))
    sysp = np.tile(pattern, 3)               # repetitive -> drafts hit
    prompts = [np.concatenate([sysp, rng.randint(1, 128, (t,))])
               for t in (4, 7)]
    cold = _mk_engine(llama_tiny, enable_prefix_cache=False,
                      num_speculative_tokens=3)
    want = cold.serve(list(prompts), max_new_tokens=8)
    want += cold.serve(list(prompts), max_new_tokens=8)
    eng = _mk_engine(llama_tiny, num_speculative_tokens=3)
    got = eng.serve(list(prompts), max_new_tokens=8)
    got += eng.serve(list(prompts), max_new_tokens=8)
    st = eng.stats()
    eng.shutdown()
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)
    assert st["prefix_blocks_reused"] > 0


def test_interleaved_prefill_matches_synchronous(llama_tiny):
    """``max_prefill_chunks_per_step`` spreads a prompt's chunks across
    engine ticks (decode keeps running for admitted slots) without
    changing a single emitted token."""
    rng = np.random.RandomState(8)
    prompts = [rng.randint(1, 128, (n,)) for n in (21, 5, 33, 9)]
    sync = _mk_engine(llama_tiny)
    want = sync.serve(list(prompts), max_new_tokens=5)
    sync.shutdown()
    eng = _mk_engine(llama_tiny, max_prefill_chunks_per_step=1)
    got = eng.serve(list(prompts), max_new_tokens=5)
    st = eng.stats()
    eng.shutdown()
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)
    assert st["prefill_chunks"] >= sum(-(-n // 8) for n in
                                       (21, 5, 33, 9))
    assert st["requests_completed"] == 4


# ------------------------------------------ one executable + kill
# switches


def test_zero_steadystate_prefill_recompiles(llama_tiny):
    """The tentpole compile bar: ONE chunked-prefill executable serves
    every prompt length — across waves of varied lengths the per-engine
    prefill compile count stays at 1 (and decode at 1)."""
    rng = np.random.RandomState(2)
    eng = _mk_engine(llama_tiny)
    eng.serve([rng.randint(1, 128, (n,)) for n in (4, 9, 23)],
              max_new_tokens=4)
    st0 = eng.stats()
    assert st0["executables_compiled"] == 1
    eng.serve([rng.randint(1, 128, (n,)) for n in (13, 2, 31, 7)],
              max_new_tokens=5)
    st1 = eng.stats()
    eng.shutdown()
    assert st1["executables_compiled"] == 1, \
        "steady-state recompile (ragged step must stay ONE executable)"
    assert st1["decode_compiles"] == 1
    assert st1["prefill_chunks"] > st0["prefill_chunks"]


def test_draft_model_prefill_is_one_executable(llama_tiny):
    """With a draft model the old path compiled a prefill zoo PER
    MODEL; chunked prefill is exactly two executables (target +
    draft), and greedy tokens still match the cold path."""
    paddle.seed(13)
    draft = LlamaForCausalLM(LlamaConfig.tiny(
        vocab=128, hidden=32, layers=1, heads=2, kv_heads=2, ffn=64))
    draft.eval()
    rng = np.random.RandomState(3)
    sysp = rng.randint(1, 128, (16,))
    prompts = [np.concatenate([sysp, rng.randint(1, 128, (t,))])
               for t in (5, 11)]

    def build(**kw):
        return ServingEngine(
            llama_tiny,
            ServingConfig(num_slots=2, block_size=8, max_model_len=96,
                          prefill_chunk=8, num_speculative_tokens=2,
                          drafter="model", **kw),
            draft_model=draft)

    cold = build(enable_prefix_cache=False)
    want = cold.serve(list(prompts), max_new_tokens=6)
    want += cold.serve(list(prompts), max_new_tokens=6)
    eng = build()
    got = eng.serve(list(prompts), max_new_tokens=6)
    got += eng.serve(list(prompts), max_new_tokens=6)
    st = eng.stats()
    eng.shutdown()
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)
    # ragged step + fused draft step (prime + proposal scan): exactly
    # two executables, down from the per-model zoo
    assert st["executables_compiled"] == 2
    assert st["prefix_blocks_reused"] > 0


def test_kill_switch_prefix_cache(llama_tiny, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PREFIX_CACHE", "0")
    rng = np.random.RandomState(0)
    prompts = _shared_prefix_prompts(rng)
    eng = _mk_engine(llama_tiny)             # config asks for caching
    eng.serve(list(prompts), max_new_tokens=4)
    eng.serve(list(prompts), max_new_tokens=4)
    st = eng.stats()
    eng.shutdown()
    assert st["prefix_cache_enabled"] is False
    assert st["prefix_blocks_reused"] == 0
    assert st["cached_blocks"] == 0
    assert st["chunked_prefill"] is True     # chunking unaffected


def test_kill_switch_chunked_prefill(llama_tiny, monkeypatch):
    """Chunked prefill off -> the legacy bucketed zoo returns (and
    prefix caching, which needs it, is forced off) with identical
    greedy tokens."""
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 128, (n,)) for n in (5, 12, 21)]
    eng = _mk_engine(llama_tiny)
    want = eng.serve(list(prompts), max_new_tokens=5)
    eng.shutdown()
    monkeypatch.setenv("PADDLE_TPU_CHUNKED_PREFILL", "0")
    leg = _mk_engine(llama_tiny)
    got = leg.serve(list(prompts), max_new_tokens=5)
    st = leg.stats()
    leg.shutdown()
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)
    assert st["chunked_prefill"] is False
    assert st["prefix_cache_enabled"] is False
    assert st["prefill_chunks"] == 0
    assert st["prefill_compiles"] >= 2       # one per bucket again


# -------------------------------------------- kernel parity + telemetry


def test_chunk_attention_kernel_matches_fallback_interpret():
    """Tier-1 guard: the multi-query kernel at CHUNK width (T = chunk
    rows, nonzero prior cached context — exactly the chunked-prefill
    shape) agrees with the gather fallback in interpret mode."""
    import jax.numpy as jnp
    from paddle_tpu.ops import paged_cache as pc
    from paddle_tpu.ops.pallas import paged_attention as pa
    if pa.pallas_paged_verify_attention is None:
        pytest.skip("pallas unavailable on this jax build")
    rng = np.random.RandomState(0)
    S, T, H, Hkv, D, BS, MB = 2, 8, 8, 4, 64, 8, 6
    NB = 1 + S * MB
    kp = jnp.asarray(rng.randn(NB, BS, Hkv, D), jnp.float32)
    vp = jnp.asarray(rng.randn(NB, BS, Hkv, D), jnp.float32)
    tables = np.zeros((S, MB), np.int32)
    # lens + 1 = chunk starts: one mid-prompt, one continuing a long
    # cached prefix (the prefix-reuse regime)
    lens = np.asarray([6, 25], np.int32)
    alloc = pc.BlockAllocator(NB)
    for s in range(S):
        n = pc.blocks_for(int(lens[s]) + T - 1, BS)
        tables[s, :n] = alloc.alloc(n)
    q = jnp.asarray(rng.randn(S, T, H, D), jnp.float32)
    ref = pa._xla_paged_verify(q, kp, vp, jnp.asarray(tables),
                               jnp.asarray(lens))
    out = pa.pallas_paged_verify_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(lens),
        interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_prefix_telemetry_in_stats_and_jsonl(tmp_path, llama_tiny):
    import json
    rng = np.random.RandomState(12)
    prompts = _shared_prefix_prompts(rng)
    eng = _mk_engine(llama_tiny)
    eng.serve(list(prompts), max_new_tokens=4)
    eng.serve(list(prompts), max_new_tokens=4)
    st = eng.stats()
    eng.shutdown()
    for k in ("prefix_blocks_reused", "prefix_tokens_reused",
              "prefix_hit_rate", "cow_copies", "cache_evictions",
              "cached_blocks", "prefill_compiles", "prefill_chunks"):
        assert k in st
    path = monitor.export_jsonl(str(tmp_path / "metrics.jsonl"))
    names = {json.loads(line)["name"] for line in open(path)}
    for want in ("serving_prefix_blocks_reused",
                 "serving_prefix_tokens_reused", "serving_cow_copies",
                 "serving_cache_evictions", "serving_prefix_hit_rate",
                 "serving_prefill_compiles"):
        assert want in names, f"{want} missing from JSONL export"


def test_tier1_no_slow_marker():
    """CI guard (the PR-4 pattern): every prefix-cache test runs in the
    tier-1 ``-m 'not slow'`` sweep, the chunk-attention kernel parity
    test exists, and engine shutdown leak-checking is exercised."""
    import tests.conftest as c
    here = open(__file__).read()
    assert "pytest.mark.slow" not in here.replace(
        '"pytest.mark.slow"', "")
    names = [ln.split("(")[0][4:] for ln in here.splitlines()
             if ln.startswith("def test_")]
    overlap = set(names) & set(c._SLOW_TESTS)
    assert not overlap, f"tier-1 prefix-cache tests marked slow: " \
                        f"{overlap}"
    assert "test_chunk_attention_kernel_matches_fallback_interpret" \
        in names
    assert here.count(".shutdown()") >= 10, \
        "engine shutdown (check_leaks) must guard these tests"
