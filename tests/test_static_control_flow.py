"""static.nn control flow + to_static graph-break fallback
(reference: ``test/dygraph_to_static`` — same model eager vs to_static,
outputs compared)."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn


def test_cond_eager_concrete_pred():
    x = paddle.to_tensor(np.array([2.0], np.float32))
    out = snn.cond(x.sum() > 1.0, lambda: x * 2, lambda: x - 1)
    np.testing.assert_allclose(out.numpy(), [4.0])
    out = snn.cond(x.sum() > 9.0, lambda: x * 2, lambda: x - 1)
    np.testing.assert_allclose(out.numpy(), [1.0])


def test_cond_under_to_static():
    @paddle.jit.to_static
    def f(x):
        return snn.cond(x.sum() > 0, lambda: x * 2, lambda: -x)

    xp = np.array([1.0, 2.0], np.float32)
    xn = np.array([-1.0, -2.0], np.float32)
    np.testing.assert_allclose(
        f(paddle.to_tensor(xp)).numpy(), xp * 2)
    np.testing.assert_allclose(
        f(paddle.to_tensor(xn)).numpy(), -xn)


def test_cond_gradient_eager():
    x = paddle.to_tensor(np.array([3.0], np.float32),
                         stop_gradient=False)
    y = snn.cond(x.sum() > 0, lambda: x * x, lambda: x)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_while_loop_eager():
    i = paddle.to_tensor(np.array(0, np.int64))
    s = paddle.to_tensor(np.array(0.0, np.float32))
    i2, s2 = snn.while_loop(lambda i, s: i < 5,
                            lambda i, s: [i + 1, s + 2.0], [i, s])
    assert int(i2.numpy()) == 5
    np.testing.assert_allclose(s2.numpy(), 10.0)


def test_while_loop_under_to_static():
    @paddle.jit.to_static
    def f(n, x):
        def cond_fn(i, acc):
            return i < n

        def body(i, acc):
            return [i + 1, acc * 2.0]

        i0 = paddle.to_tensor(np.array(0, np.int64))
        _, acc = snn.while_loop(cond_fn, body, [i0, x])
        return acc

    x = paddle.to_tensor(np.array([1.0], np.float32))
    out = f(paddle.to_tensor(np.array(3, np.int64)), x)
    np.testing.assert_allclose(out.numpy(), [8.0])
    out = f(paddle.to_tensor(np.array(5, np.int64)), x)
    np.testing.assert_allclose(out.numpy(), [32.0])


def test_switch_case():
    x = paddle.to_tensor(np.array([1.0], np.float32))
    fns = {0: lambda: x + 1, 2: lambda: x + 2}
    np.testing.assert_allclose(
        snn.switch_case(paddle.to_tensor(np.array(2, np.int64)), fns,
                        default=lambda: x).numpy(), [3.0])
    np.testing.assert_allclose(
        snn.switch_case(paddle.to_tensor(np.array(7, np.int64)), fns,
                        default=lambda: x).numpy(), [1.0])

    @paddle.jit.to_static
    def f(i):
        return snn.switch_case(i, {0: lambda: x + 1, 2: lambda: x + 2},
                               default=lambda: x * 10)

    np.testing.assert_allclose(
        f(paddle.to_tensor(np.array(0, np.int64))).numpy(), [2.0])
    np.testing.assert_allclose(
        f(paddle.to_tensor(np.array(3, np.int64))).numpy(), [10.0])


def test_case_first_true_wins():
    x = paddle.to_tensor(np.array([5.0], np.float32))
    out = snn.case([(x.sum() > 10, lambda: x * 0),
                    (x.sum() > 1, lambda: x * 2)],
                   default=lambda: x)
    np.testing.assert_allclose(out.numpy(), [10.0])


def test_to_static_graph_break_falls_back_eager():
    calls = []

    @paddle.jit.to_static
    def f(x):
        calls.append(1)
        if float(x.sum().numpy()) > 0:  # untraceable host read
            return x * 2
        return -x

    x = paddle.to_tensor(np.array([1.0], np.float32))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = f(x)
        assert any("falling back to eager" in str(m.message) for m in w)
    np.testing.assert_allclose(out.numpy(), [2.0])
    # subsequent calls run eagerly without re-warning
    out2 = f(paddle.to_tensor(np.array([-1.0], np.float32)))
    np.testing.assert_allclose(out2.numpy(), [1.0])
