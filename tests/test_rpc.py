"""paddle.distributed.rpc (reference ``python/paddle/distributed/rpc``
— tested with real worker subprocesses per the reference pattern)."""
import os
import subprocess
import sys

import numpy as np


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_rpc_two_workers(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        "import numpy as np\n"
        "import paddle_tpu.distributed.rpc as rpc\n"
        "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "info = rpc.init_rpc(f'worker{rank}')\n"
        "assert rpc.get_worker_info().rank == rank\n"
        "assert len(rpc.get_all_worker_infos()) == 2\n"
        "if rank == 0:\n"
        "    out = rpc.rpc_sync('worker1', pow, args=(2, 10))\n"
        "    assert out == 1024, out\n"
        "    fut = rpc.rpc_async(1, max, args=(3, 7))\n"
        "    assert fut.wait() == 7\n"
        "    try:\n"
        "        rpc.rpc_sync('worker1', int, args=('nope',))\n"
        "        raise AssertionError('callee error not raised')\n"
        "    except ValueError:\n"
        "        pass\n"
        "    print('RPC-OK')\n"
        "rpc.shutdown()\n")
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({"PADDLE_TRAINER_ID": str(rank),
                    "PADDLE_TRAINERS_NUM": "2",
                    "PADDLE_MASTER": f"127.0.0.1:{port}",
                    "JAX_PLATFORMS": "cpu",
                    "PYTHONPATH": repo_root})
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = [p.communicate(timeout=120)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    assert "RPC-OK" in outs[0]
