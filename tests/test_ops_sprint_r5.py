"""Round-5 op-surface sprint tests: paddle.geometric, igamma/igammac,
sparse mask_as/CSR, HSigmoidLoss / RNNTLoss / BeamSearchDecoder layer
classes, and nn.quant weight-only int8.

References: ``python/paddle/geometric/``, ``paddle/phi/kernels/sparse/``,
``python/paddle/nn/layer/loss.py``, ``python/paddle/nn/decode.py``,
``python/paddle/nn/quant/quantized_linear.py``.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle


# ---------------------------------------------------------------- geometric

def test_segment_ops_oracle():
    data = np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]], np.float32)
    ids = np.array([0, 0, 1, 3], np.int64)          # segment 2 empty
    d = paddle.to_tensor(data)
    i = paddle.to_tensor(ids)
    s = paddle.geometric.segment_sum(d, i)
    np.testing.assert_allclose(
        s.numpy(), [[4., 6.], [5., 6.], [0., 0.], [7., 8.]])
    m = paddle.geometric.segment_mean(d, i)
    np.testing.assert_allclose(
        m.numpy(), [[2., 3.], [5., 6.], [0., 0.], [7., 8.]])
    mx = paddle.geometric.segment_max(d, i)
    np.testing.assert_allclose(
        mx.numpy(), [[3., 4.], [5., 6.], [0., 0.], [7., 8.]])
    mn = paddle.geometric.segment_min(d, i)
    np.testing.assert_allclose(
        mn.numpy(), [[1., 2.], [5., 6.], [0., 0.], [7., 8.]])


def test_segment_sum_grad():
    data = paddle.to_tensor(
        np.arange(8, dtype=np.float32).reshape(4, 2))
    data.stop_gradient = False
    ids = paddle.to_tensor(np.array([0, 1, 1, 2], np.int64))
    out = paddle.geometric.segment_sum(data, ids)
    out.sum().backward()
    np.testing.assert_allclose(data.grad.numpy(), np.ones((4, 2)))


def test_send_u_recv_oracle():
    x = np.array([[1.], [2.], [4.]], np.float32)
    src = np.array([0, 1, 2, 0], np.int64)
    dst = np.array([1, 2, 1, 0], np.int64)
    out = paddle.geometric.send_u_recv(
        paddle.to_tensor(x), paddle.to_tensor(src),
        paddle.to_tensor(dst), reduce_op="sum")
    # dst 0 <- x[0]=1; dst 1 <- x[0]+x[2]=5; dst 2 <- x[1]=2
    np.testing.assert_allclose(out.numpy(), [[1.], [5.], [2.]])
    out_max = paddle.geometric.send_u_recv(
        paddle.to_tensor(x), paddle.to_tensor(src),
        paddle.to_tensor(dst), reduce_op="max")
    np.testing.assert_allclose(out_max.numpy(), [[1.], [4.], [2.]])


def test_send_ue_recv_and_send_uv():
    x = np.array([[1.], [2.], [3.]], np.float32)
    e = np.array([[10.], [20.], [30.]], np.float32)
    src = np.array([0, 1, 2], np.int64)
    dst = np.array([1, 2, 0], np.int64)
    out = paddle.geometric.send_ue_recv(
        paddle.to_tensor(x), paddle.to_tensor(e),
        paddle.to_tensor(src), paddle.to_tensor(dst),
        message_op="add", reduce_op="sum")
    np.testing.assert_allclose(out.numpy(), [[33.], [11.], [22.]])
    uv = paddle.geometric.send_uv(
        paddle.to_tensor(x), paddle.to_tensor(x),
        paddle.to_tensor(src), paddle.to_tensor(dst),
        message_op="mul")
    np.testing.assert_allclose(uv.numpy(), [[2.], [6.], [3.]])


# ------------------------------------------------------------------- igamma

def test_igamma_igammac():
    from scipy import special
    x = np.array([0.5, 1.0, 2.0, 5.0], np.float32)
    a = np.array([1.0, 2.0, 1.5, 3.0], np.float32)
    up = paddle.igamma(paddle.to_tensor(x), paddle.to_tensor(a))
    lo = paddle.igammac(paddle.to_tensor(x), paddle.to_tensor(a))
    np.testing.assert_allclose(up.numpy(), special.gammaincc(x, a),
                               rtol=1e-5)
    np.testing.assert_allclose(lo.numpy(), special.gammainc(x, a),
                               rtol=1e-5)
    np.testing.assert_allclose(up.numpy() + lo.numpy(),
                               np.ones_like(x), rtol=1e-5)


# ------------------------------------------------------------------- sparse

def test_sparse_mask_as_coo_and_csr():
    dense = paddle.to_tensor(
        np.arange(12, dtype=np.float32).reshape(3, 4))
    coo = paddle.sparse.sparse_coo_tensor(
        [[0, 1, 2], [1, 2, 3]], [1., 1., 1.], (3, 4))
    m = paddle.sparse.mask_as(dense, coo)
    np.testing.assert_allclose(np.asarray(m.values().numpy()),
                               [1., 6., 11.])
    csr = paddle.sparse.sparse_csr_tensor(
        [0, 1, 2, 3], [1, 2, 3], [1., 1., 1.], (3, 4))
    assert csr.is_sparse_csr()
    m2 = paddle.sparse.mask_as(dense, csr)
    assert m2.is_sparse_csr()
    np.testing.assert_allclose(np.asarray(m2.values().numpy()),
                               [1., 6., 11.])
    np.testing.assert_allclose(np.asarray(m2.crows().numpy()),
                               [0, 1, 2, 3])
    np.testing.assert_allclose(m2.to_dense().numpy(),
                               dense.numpy() * coo.to_dense().numpy())


# ----------------------------------------------------------- HSigmoidLoss

def test_hsigmoid_loss_layer():
    paddle.seed(0)
    layer = paddle.nn.HSigmoidLoss(feature_size=8, num_classes=6)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(np.array([0, 2, 4, 5], np.int64))
    out = layer(x, y)
    assert tuple(out.shape) == (4, 1)
    assert np.all(np.isfinite(out.numpy())) and np.all(out.numpy() > 0)
    # trainable: loss reduces under SGD on the layer params
    x.stop_gradient = False
    out.sum().backward()
    assert layer.weight.grad is not None


# -------------------------------------------------------------- RNNT loss

def _rnnt_ref(logits, labels, t_len, u_len, blank=0, femit=0.0):
    """Brute numpy forward-variable DP (log-space)."""
    def lse(a, b):
        m = max(a, b)
        if m == -np.inf:
            return -np.inf
        return m + np.log(np.exp(a - m) + np.exp(b - m))
    B = logits.shape[0]
    out = []
    for b in range(B):
        T, U1 = t_len[b], u_len[b] + 1
        lp = logits[b] - np.log(
            np.exp(logits[b]).sum(-1, keepdims=True))
        if femit:
            lp = lp.copy()
        alpha = np.full((T, U1), -np.inf)
        alpha[0, 0] = 0.0
        for t in range(T):
            for u in range(U1):
                if t > 0:
                    alpha[t, u] = lse(alpha[t, u],
                                      alpha[t - 1, u]
                                      + lp[t - 1, u, blank])
                if u > 0:
                    em = lp[t, u - 1, labels[b, u - 1]] \
                        + (np.log1p(femit) if femit else 0.0)
                    alpha[t, u] = lse(alpha[t, u],
                                      alpha[t, u - 1] + em)
        out.append(-(alpha[T - 1, U1 - 1]
                     + lp[T - 1, U1 - 1, blank]))
    return np.array(out, np.float32)


def test_rnnt_loss_matches_reference_dp():
    rng = np.random.RandomState(0)
    B, T, U, V = 3, 5, 3, 7
    logits = rng.randn(B, T, U + 1, V).astype(np.float32)
    labels = rng.randint(1, V, (B, U)).astype(np.int64)
    t_len = np.array([5, 4, 3], np.int64)
    u_len = np.array([3, 2, 3], np.int64)
    ref = _rnnt_ref(logits, labels, t_len, u_len)
    out = paddle.nn.functional.rnnt_loss(
        paddle.to_tensor(logits), paddle.to_tensor(labels),
        paddle.to_tensor(t_len), paddle.to_tensor(u_len),
        fastemit_lambda=0.0, reduction="none")
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)
    # layer wrapper + mean reduction + differentiability
    layer = paddle.nn.RNNTLoss(fastemit_lambda=0.0)
    lt = paddle.to_tensor(logits)
    lt.stop_gradient = False
    loss = layer(lt, paddle.to_tensor(labels), paddle.to_tensor(t_len),
                 paddle.to_tensor(u_len))
    np.testing.assert_allclose(float(loss.numpy()), ref.mean(),
                               rtol=1e-4)
    loss.backward()
    assert lt.grad is not None
    assert np.all(np.isfinite(lt.grad.numpy()))


# ------------------------------------------------------ BeamSearchDecoder

def test_beam_search_decoder_dynamic_decode():
    paddle.seed(7)
    V, H, B, K = 12, 16, 2, 3
    cell = paddle.nn.LSTMCell(H, H)
    emb = paddle.nn.Embedding(V, H)
    proj = paddle.nn.Linear(H, V)
    dec = paddle.nn.BeamSearchDecoder(
        cell, start_token=1, end_token=2, beam_size=K,
        embedding_fn=emb, output_fn=proj)
    h0 = paddle.to_tensor(
        np.random.RandomState(1).randn(B, H).astype(np.float32))
    c0 = paddle.zeros([B, H])
    ids, states, lengths = paddle.nn.dynamic_decode(
        dec, inits=(h0, c0), max_step_num=8, return_length=True)
    assert tuple(ids.shape)[0] == B and tuple(ids.shape)[1] == K
    assert tuple(ids.shape)[2] <= 8
    ln = lengths.numpy()
    assert ln.shape == (B, K) and np.all(ln >= 1)
    # every finished beam's sequence ends with the end token
    arr = ids.numpy()
    for b in range(B):
        for k in range(K):
            if ln[b, k] < arr.shape[-1]:
                assert arr[b, k, ln[b, k] - 1] == 2


# ---------------------------------------------------------------- nn.quant

def test_weight_quantize_and_linear():
    rng = np.random.RandomState(0)
    W = paddle.to_tensor(rng.randn(64, 32).astype(np.float32))
    x = paddle.to_tensor(rng.randn(4, 64).astype(np.float32))
    qw, s = paddle.nn.quant.weight_quantize(W, "weight_only_int8")
    assert qw.numpy().dtype == np.int8
    y = paddle.nn.quant.weight_only_linear(x, qw, None, s)
    ref = x.numpy() @ W.numpy()
    rel = np.max(np.abs(y.numpy() - ref)) / np.max(np.abs(ref))
    assert rel < 0.02
    deq = paddle.nn.quant.weight_dequantize(qw, s, out_dtype="float32")
    rel_w = np.max(np.abs(deq.numpy() - W.numpy())) \
        / np.max(np.abs(W.numpy()))
    assert rel_w < 0.01


def test_quantize_for_inference_swaps_and_generates():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=96, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64)
    paddle.seed(0)
    m = LlamaForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 128, (2, 8)).astype(np.int64))
    out_ref, _ = m.generate(ids, max_new_tokens=4)   # warm the cache
    n = paddle.nn.quant.quantize_for_inference(m)
    assert n >= 10                                   # all proj layers
    out_q, _ = m.generate(ids, max_new_tokens=4)     # stale cache purged
    assert out_q.numpy().shape == out_ref.numpy().shape
