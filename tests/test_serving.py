"""Continuous-batching serving engine + paged KV cache (ISSUE 3):
paged-vs-dense greedy parity across mixed prompt lengths, scheduler
properties (every request completes exactly once, no block-pool leaks),
zero steady-state recompiles, generate() prompt bucketing, the ragged
Pallas kernel in interpret mode, and the fused int8 decode matmul."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.inference import ServingConfig, ServingEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture
def llama_tiny():
    paddle.seed(7)
    cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                           kv_heads=2, ffn=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _dense_ref(model, prompt, n):
    out, _ = model.generate(paddle.to_tensor(
        np.asarray(prompt, np.int64)[None]), max_new_tokens=n)
    return np.asarray(out.numpy())[0]


# ---------------------------------------------------------------- paged
# cache primitives


def test_block_allocator_reuse_and_errors():
    from paddle_tpu.ops.paged_cache import BlockAllocator
    a = BlockAllocator(8)              # blocks 1..7 usable
    assert a.free_blocks == 7
    got = a.alloc(7)
    assert sorted(got) == list(range(1, 8))
    with pytest.raises(RuntimeError, match="exhausted"):
        a.alloc(1)
    a.free(got[:3])
    assert a.free_blocks == 3
    with pytest.raises(ValueError, match="double free"):
        a.free([got[0]])
    with pytest.raises(ValueError, match="invalid"):
        a.free([0])                    # the null block is never freed
    # prefix-cache back-compat contract: a freed-but-PUBLISHED block
    # parks in the LRU cache yet still counts as free (admission
    # reservations see it; alloc reclaims it transparently)
    a.publish(got[3], b"h3")
    a.free([got[3]])
    assert a.free_blocks == 4 and a.cached_blocks == 1
    assert a.lookup(b"h3") == got[3]
    a.alloc(4)                         # eviction makes it allocatable
    assert a.lookup(b"h3") is None and a.evictions == 1


def test_paged_write_gather_roundtrip():
    import jax.numpy as jnp
    from paddle_tpu.ops import paged_cache as pc
    rng = np.random.RandomState(0)
    BS, MB, H, D = 4, 3, 2, 8
    kp, vp = pc.init_pool(1 + 2 * MB, BS, H, D, jnp.float32)
    tables = jnp.asarray(
        (1 + np.arange(2 * MB, dtype=np.int32)).reshape(2, MB))
    k = jnp.asarray(rng.randn(2, 10, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(2, 10, H, D), jnp.float32)
    kp, vp = pc.write_prefill(kp, vp, tables, k, v,
                              n_real=np.asarray([10, 7]))
    dense_k = pc.gather_dense(kp, tables)
    np.testing.assert_allclose(np.asarray(dense_k[0, :10]),
                               np.asarray(k[0]))
    np.testing.assert_allclose(np.asarray(dense_k[1, :7]),
                               np.asarray(k[1, :7]))
    # row 1 positions >= 7 went to the null block, not its own blocks
    assert not np.allclose(np.asarray(dense_k[1, 7:10]),
                           np.asarray(k[1, 7:10]))
    # decode write lands at each slot's own position
    k1 = jnp.asarray(rng.randn(2, H, D), jnp.float32)
    v1 = jnp.asarray(rng.randn(2, H, D), jnp.float32)
    kp, vp = pc.write_decode(kp, vp, tables,
                             jnp.asarray([10, 7], jnp.int32), k1, v1)
    dense_k = pc.gather_dense(kp, tables)
    np.testing.assert_allclose(np.asarray(dense_k[0, 10]),
                               np.asarray(k1[0]))
    np.testing.assert_allclose(np.asarray(dense_k[1, 7]),
                               np.asarray(k1[1]))


def test_pallas_paged_kernel_matches_fallback_interpret():
    """The ragged TPU kernel (run in interpret mode on CPU) must agree
    with the gather fallback on ragged lengths + GQA."""
    import jax.numpy as jnp
    from paddle_tpu.ops import paged_cache as pc
    from paddle_tpu.ops.pallas import paged_attention as pa
    if pa.pallas_paged_attention is None:
        pytest.skip("pallas unavailable on this jax build")
    rng = np.random.RandomState(0)
    S, H, Hkv, D, BS, MB = 3, 8, 4, 64, 8, 4
    NB = 1 + S * MB
    kp = jnp.asarray(rng.randn(NB, BS, Hkv, D), jnp.float32)
    vp = jnp.asarray(rng.randn(NB, BS, Hkv, D), jnp.float32)
    tables = np.zeros((S, MB), np.int32)
    lens = np.asarray([5, 17, 29], np.int32)
    alloc = pc.BlockAllocator(NB)
    for s in range(S):
        n = pc.blocks_for(int(lens[s]), BS)
        tables[s, :n] = alloc.alloc(n)
    q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
    ref = pa._xla_paged_attention(q, kp, vp, jnp.asarray(tables),
                                  jnp.asarray(lens))
    out = pa.pallas_paged_attention(q, kp, vp, jnp.asarray(tables),
                                    jnp.asarray(lens), interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


# ------------------------------------------------------------ generate()
# paged + bucketing


def test_generate_paged_matches_dense(llama_tiny):
    """generate(cache_impl='paged') must reproduce the dense decode
    token-for-token (the block-pool layout is a pure re-layout)."""
    ids = np.random.RandomState(0).randint(0, 128, (2, 9)) \
        .astype(np.int64)
    dense, sd = llama_tiny.generate(paddle.to_tensor(ids),
                                    max_new_tokens=6)
    paged, sp = llama_tiny.generate(paddle.to_tensor(ids),
                                    max_new_tokens=6,
                                    cache_impl="paged")
    np.testing.assert_array_equal(dense.numpy(), paged.numpy())
    np.testing.assert_allclose(np.asarray(sd.numpy()),
                               np.asarray(sp.numpy()), atol=1e-4)


def test_generate_paged_rejects_beam_and_mask(llama_tiny):
    ids = paddle.to_tensor(np.zeros((1, 4), np.int64))
    with pytest.raises(NotImplementedError, match="beam"):
        llama_tiny.generate(ids, decode_strategy="beam_search",
                            num_beams=2, max_new_tokens=2,
                            cache_impl="paged")
    with pytest.raises(NotImplementedError, match="left-padded"):
        llama_tiny.generate(ids, max_new_tokens=2, cache_impl="paged",
                            attention_mask=paddle.to_tensor(
                                np.ones((1, 4), np.int64)))


def test_generate_bucketing_reuses_executable(llama_tiny):
    """Prompt lengths in one power-of-two bucket share ONE compiled
    decode loop: the second length must be a jit-cache HIT (the r5 gap:
    every exact length compiled fresh)."""
    c = monitor.counter("generate_jit_cache", labels=("model", "event"))
    rng = np.random.RandomState(3)

    def counts():
        return (c.labels(model="LlamaForCausalLM", event="miss").value(),
                c.labels(model="LlamaForCausalLM", event="hit").value())

    ids9 = rng.randint(0, 128, (2, 9)).astype(np.int64)
    llama_tiny.generate(paddle.to_tensor(ids9), max_new_tokens=4)
    m0, h0 = counts()
    for plen in (10, 12, 15):          # all bucket to 16, like 9
        ids = rng.randint(0, 128, (2, plen)).astype(np.int64)
        llama_tiny.generate(paddle.to_tensor(ids), max_new_tokens=4)
    m1, h1 = counts()
    assert m1 == m0, "bucketed prompt lengths must not recompile"
    assert h1 == h0 + 3


def test_generate_bucketing_matches_exact(llama_tiny):
    """Bucketing must not change the generated tokens (it rides the
    proven left-padded path)."""
    ids = np.random.RandomState(5).randint(0, 128, (2, 11)) \
        .astype(np.int64)
    bucketed, _ = llama_tiny.generate(paddle.to_tensor(ids),
                                      max_new_tokens=5)
    exact, _ = llama_tiny.generate(paddle.to_tensor(ids),
                                   max_new_tokens=5,
                                   pad_prompt_to_bucket=False)
    np.testing.assert_array_equal(bucketed.numpy(), exact.numpy())


# -------------------------------------------------------------- serving
# engine


def test_serving_parity_mixed_lengths(llama_tiny):
    """Batch-served greedy tokens must match each prompt generated alone
    through the dense cache — token for token, across prompt lengths
    that span buckets and block boundaries."""
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 128, (n,)).astype(np.int64)
               for n in (5, 9, 13, 7, 21, 3)]
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=3, block_size=8, max_model_len=64, max_new_tokens=6,
        min_prefill_bucket=8))
    outs = eng.serve(prompts, max_new_tokens=6)
    for p, got in zip(prompts, outs):
        ref = _dense_ref(llama_tiny, p, 6)
        np.testing.assert_array_equal(got, ref[:len(got)])


def test_serving_scheduler_property(llama_tiny):
    """Scheduler invariants under slot + block pressure: every submitted
    request completes exactly once, streamed tokens equal the returned
    tokens, and the block pool drains to empty (no leaks)."""
    rng = np.random.RandomState(1)
    cfg = ServingConfig(num_slots=2, block_size=8, max_model_len=48,
                        num_blocks=13, min_prefill_bucket=8)
    streamed = {}
    eng = ServingEngine(
        llama_tiny, cfg,
        stream_callback=lambda rid, t: streamed.setdefault(rid, [])
        .append(t))
    rids = []
    lens = [3, 11, 6, 17, 9, 2, 14, 5]
    news = [4, 7, 1, 5, 3, 8, 2, 6]
    for n, mn in zip(lens, news):
        rids.append(eng.submit(rng.randint(1, 128, (n,)), mn))
    done = eng.run()
    assert sorted(done) == sorted(rids), "each request completes once"
    for rid, mn in zip(rids, news):
        assert 1 <= len(done[rid]) <= mn
        assert streamed[rid] == list(done[rid])
    st = eng.stats()
    assert st["active"] == 0 and st["queued"] == 0
    assert st["reserved_blocks"] == 0
    assert st["free_blocks"] == cfg.num_blocks - 1, "block-pool leak"
    assert st["requests_completed"] == len(rids)


def test_serving_zero_steadystate_recompiles(llama_tiny):
    """The serving bar: after warmup, the decode executable never
    recompiles — the compile counter stays at 1 while the step counter
    keeps growing (fixed-slot static shapes)."""
    rng = np.random.RandomState(2)
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        min_prefill_bucket=8))
    eng.serve([rng.randint(1, 128, (n,)) for n in (4, 9)],
              max_new_tokens=4)
    st0 = eng.stats()
    assert st0["decode_compiles"] == 1
    # second wave: different lengths/occupancy mixes, same executable
    eng.serve([rng.randint(1, 128, (n,)) for n in (13, 2, 7)],
              max_new_tokens=5)
    st1 = eng.stats()
    assert st1["decode_compiles"] == 1, "steady-state recompile"
    assert st1["decode_steps"] > st0["decode_steps"]


def test_serving_eos_retires_slot(llama_tiny):
    rng = np.random.RandomState(4)
    prompt = rng.randint(1, 128, (5,))
    first = int(_dense_ref(llama_tiny, prompt, 1)[0])
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        eos_token_id=first, min_prefill_bucket=8))
    (out,) = eng.serve([prompt], max_new_tokens=8)
    assert out.tolist() == [first]     # stopped right at EOS
    assert eng.stats()["free_blocks"] == eng._alloc.num_blocks - 1


def test_serving_gpt_family(llama_tiny):
    """GPT rides the same paged path (MHA, learned positions)."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(3)
    m = GPTForCausalLM(GPTConfig.tiny(vocab=96, hidden=64, layers=2,
                                      heads=4))
    m.eval()
    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, 96, (n,)).astype(np.int64)
               for n in (5, 11, 8)]
    eng = ServingEngine(m, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        min_prefill_bucket=8))
    outs = eng.serve(prompts, max_new_tokens=4)
    for p, got in zip(prompts, outs):
        ref = _dense_ref(m, p, 4)
        np.testing.assert_array_equal(got, ref[:len(got)])


def test_serving_streaming_mode_drops_results(llama_tiny):
    """``retain_results=False`` (long-lived streaming deployments):
    tokens reach the callback but retirement drops the per-request
    buffer — nothing accumulates, ``run()`` returns {}."""
    rng = np.random.RandomState(13)
    streamed = {}
    eng = ServingEngine(
        llama_tiny,
        ServingConfig(num_slots=2, block_size=8, max_model_len=64,
                      retain_results=False),
        stream_callback=lambda rid, t: streamed.setdefault(rid, [])
        .append(t))
    rids = [eng.submit(rng.randint(1, 128, (n,)), 4) for n in (5, 9, 3)]
    done = eng.run()
    assert done == {}
    assert eng._done == {} and eng._results == {}
    for rid in rids:
        assert 1 <= len(streamed[rid]) <= 4
    assert eng.stats()["requests_completed"] == 3


def test_serving_validates_requests(llama_tiny):
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=2, block_size=8, max_model_len=32))
    with pytest.raises(ValueError, match="max_model_len"):
        eng.submit(np.arange(1, 30), max_new_tokens=8)
    with pytest.raises(ValueError, match="empty"):
        eng.submit([])
    import paddle_tpu.nn as nn
    with pytest.raises(TypeError):
        ServingEngine(nn.Linear(4, 4))


def test_serving_telemetry_in_jsonl(tmp_path, llama_tiny):
    """The serving gauges/histograms/counters land in the monitor JSONL
    export (the ops-dashboard contract)."""
    import json
    rng = np.random.RandomState(6)
    eng = ServingEngine(llama_tiny, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        min_prefill_bucket=8))
    eng.serve([rng.randint(1, 128, (n,)) for n in (4, 12, 6)],
              max_new_tokens=4)
    path = monitor.export_jsonl(str(tmp_path / "metrics.jsonl"))
    names = {json.loads(line)["name"] for line in open(path)}
    for want in ("serving_slot_occupancy", "serving_batch_utilization",
                 "serving_queue_wait_ms", "serving_tokens_total",
                 "serving_decode_steps", "serving_decode_compiles",
                 "serving_requests_completed", "generate_jit_cache"):
        assert want in names, f"{want} missing from JSONL export"


# ----------------------------------------------------------- fused int8


def test_weight_only_int8_fused_matches_dequant():
    """The fused mixed-dtype dot (int8 weights straight into
    lax.dot_general, scale post-matmul) must match the explicit
    dequantize-then-matmul reference."""
    rng = np.random.RandomState(0)
    W = paddle.to_tensor(rng.randn(64, 48).astype(np.float32))
    x = paddle.to_tensor(rng.randn(4, 64).astype(np.float32))
    bias = paddle.to_tensor(rng.randn(48).astype(np.float32))
    qw, s = paddle.nn.quant.weight_quantize(W, "weight_only_int8")
    ref_w = paddle.nn.quant.weight_dequantize(qw, s,
                                              out_dtype="float32")
    ref = np.asarray(x.numpy()) @ np.asarray(ref_w.numpy()) \
        + np.asarray(bias.numpy())
    y = paddle.nn.quant.weight_only_linear(x, qw, bias, s)
    np.testing.assert_allclose(np.asarray(y.numpy()), ref,
                               rtol=1e-4, atol=1e-4)


def test_int8_teacher_forced_trajectory_floor(llama_tiny):
    """The fused int8 path's greedy trajectory agreement with bf16 must
    stay >= the r5 bench value (int8_trajectory_match = 0.1665 in
    BENCH_r05.json) — the satellite regression pin for the fused
    rewrite. Teacher-forced argmax agreement is also pinned (the
    less-chaotic metric the bench reports alongside)."""
    from paddle_tpu.nn.quant import quantize_for_inference
    ids = np.random.RandomState(8).randint(0, 128, (4, 12)) \
        .astype(np.int64)
    x = paddle.to_tensor(ids)
    bf_out, _ = llama_tiny.generate(x, max_new_tokens=16)
    bf_seq = np.concatenate([ids, np.asarray(bf_out.numpy())], axis=1)
    logits_bf = llama_tiny(paddle.to_tensor(bf_seq)).numpy()
    n = quantize_for_inference(llama_tiny)
    assert n > 0
    logits_q = llama_tiny(paddle.to_tensor(bf_seq)).numpy()
    forced = float((np.asarray(logits_bf).argmax(-1)
                    == np.asarray(logits_q).argmax(-1)).mean())
    q_out, _ = llama_tiny.generate(x, max_new_tokens=16)
    traj = float((np.asarray(bf_out.numpy())
                  == np.asarray(q_out.numpy())).mean())
    assert forced >= 0.9, f"teacher-forced parity collapsed: {forced}"
    assert traj >= 0.1665, f"trajectory match below r5 floor: {traj}"


def test_serving_int8_quantized_model():
    """The engine serves a weight-only-int8 model through the same
    compiled decode step (the production int8 serving mode)."""
    from paddle_tpu.nn.quant import quantize_for_inference
    paddle.seed(11)
    cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                           kv_heads=2, ffn=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    quantize_for_inference(m)
    rng = np.random.RandomState(9)
    prompts = [rng.randint(1, 128, (n,)).astype(np.int64)
               for n in (6, 10)]
    eng = ServingEngine(m, ServingConfig(
        num_slots=2, block_size=8, max_model_len=64,
        min_prefill_bucket=8))
    outs = eng.serve(prompts, max_new_tokens=4)
    for p, got in zip(prompts, outs):
        ref = _dense_ref(m, p, 4)
        np.testing.assert_array_equal(got, ref[:len(got)])
