"""jit.to_static / TrainStep parity with eager; AMP behavior."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _mlp():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))


def test_to_static_matches_eager():
    net = _mlp()
    x = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
    eager = net(x).numpy()
    net_static = paddle.jit.to_static(net)
    static = net_static(x).numpy()
    np.testing.assert_allclose(eager, static, rtol=1e-5, atol=1e-6)


def test_to_static_function():
    @paddle.jit.to_static
    def f(a, b):
        return paddle.matmul(a, b) + 1.0

    a = paddle.to_tensor(np.random.rand(2, 3).astype(np.float32))
    b = paddle.to_tensor(np.random.rand(3, 2).astype(np.float32))
    np.testing.assert_allclose(f(a, b).numpy(),
                               a.numpy() @ b.numpy() + 1, rtol=1e-5)


def test_trainstep_matches_eager_sgd():
    np.random.seed(0)
    x = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
    y = paddle.to_tensor(np.random.rand(8, 2).astype(np.float32))
    loss_fn = nn.MSELoss()

    # eager
    net1 = _mlp()
    opt1 = paddle.optimizer.SGD(0.1, parameters=net1.parameters())
    losses1 = []
    for _ in range(5):
        loss = loss_fn(net1(x), y)
        loss.backward()
        opt1.step()
        opt1.clear_grad()
        losses1.append(float(loss))

    # jitted TrainStep
    net2 = _mlp()
    opt2 = paddle.optimizer.SGD(0.1, parameters=net2.parameters())
    from paddle_tpu.jit import TrainStep
    step = TrainStep(net2, lambda out, a, k: loss_fn(out,
                                                     paddle.Tensor(
                                                         k["_labels"][0])),
                     opt2)
    losses2 = [float(step(x, _labels=(y,))) for _ in range(5)]
    np.testing.assert_allclose(losses1, losses2, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(net1[0].weight.numpy(),
                               net2[0].weight.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_trainstep_adamw_state_advances():
    net = _mlp()
    opt = paddle.optimizer.AdamW(0.01, parameters=net.parameters())
    from paddle_tpu.jit import TrainStep
    loss_fn = nn.MSELoss()
    x = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
    y = paddle.to_tensor(np.random.rand(4, 2).astype(np.float32))
    step = TrainStep(net, lambda out, a, k: loss_fn(
        out, paddle.Tensor(k["_labels"][0])), opt)
    l0 = float(step(x, _labels=(y,)))
    for _ in range(20):
        l = float(step(x, _labels=(y,)))
    assert l < l0


def test_autocast_o1_matmul_bf16():
    a = paddle.to_tensor(np.random.rand(2, 2).astype(np.float32))
    b = paddle.to_tensor(np.random.rand(2, 2).astype(np.float32))
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        out = paddle.matmul(a, b)
    assert out.dtype == paddle.bfloat16
    out2 = paddle.matmul(a, b)
    assert out2.dtype == paddle.float32


def test_autocast_blacklist_stays_fp32():
    x = paddle.to_tensor(np.random.rand(4).astype(np.float32))
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        out = paddle.nn.functional.softmax(x)
    assert out.dtype == paddle.float32


def test_amp_decorate_o2():
    net = _mlp()
    opt = paddle.optimizer.AdamW(0.01, parameters=net.parameters())
    net, opt = paddle.amp.decorate(net, opt, level="O2", dtype="bfloat16")
    assert net[0].weight.dtype == paddle.bfloat16
    assert opt._multi_precision


def test_grad_scaler_protocol():
    net = _mlp()
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
    x = paddle.to_tensor(np.random.rand(2, 4).astype(np.float32))
    loss = net(x).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    before = net[0].weight.numpy().copy()
    scaler.step(opt)
    assert not np.allclose(before, net[0].weight.numpy())


def test_bn_buffers_update_under_trainstep():
    net = nn.Sequential(nn.Conv2D(1, 2, 3, padding=1), nn.BatchNorm2D(2),
                        nn.Flatten(), nn.Linear(2 * 4 * 4, 2))
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    from paddle_tpu.jit import TrainStep
    loss_fn = nn.CrossEntropyLoss()
    x = paddle.to_tensor(np.random.rand(4, 1, 4, 4).astype(np.float32))
    y = paddle.to_tensor(np.array([0, 1, 0, 1], np.int64))
    bn = net[1]
    mean_before = bn._mean.numpy().copy()
    step = TrainStep(net, lambda out, a, k: loss_fn(
        out, paddle.Tensor(k["_labels"][0])), opt)
    step(x, _labels=(y,))
    assert not np.allclose(mean_before, bn._mean.numpy())
