// TCPStore: blocking key-value rendezvous over TCP.
//
// TPU-native counterpart of the reference's bootstrap store
// (paddle/fluid/distributed/store/tcp_store.cc): rank0 hosts the store,
// every rank set()s its endpoint and get()s peers'; get blocks until the
// key exists, add() is the atomic barrier counter. Exposed as a C API for
// ctypes (no pybind11 in this image).
//
// Server: one accept loop + thread-per-connection; state is a
// mutex-guarded map with a condition_variable so blocking gets/waits
// park inside their connection thread.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// untrusted length-prefix ceiling: rendezvous values are tiny
// (endpoints, ranks); 64 MiB leaves headroom without letting a rogue
// peer OOM rank 0 with a 4 GiB allocation
constexpr uint64_t kMaxValLen = 64ull << 20;

enum Cmd : uint8_t {
  kSet = 1,
  kGet = 2,      // blocking until key exists
  kAdd = 3,
  kWait = 4,     // blocking until key exists, no value returned
  kDelete = 5,
  kNumKeys = 6,
  kTryGet = 7,   // non-blocking get
};

enum Status : uint8_t { kOk = 0, kTimeout = 1, kMissing = 2, kErr = 3 };

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Server {
  int listen_fd = -1;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;
  std::mutex conns_mu;

  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<char>> kv;

  // etcd-durability parity: when set, every mutation rewrites the whole
  // map to <snapshot_path> (tmp + rename, crash-atomic). Rendezvous
  // maps are tiny (endpoints, heartbeats), so whole-map rewrite per
  // mutation is cheaper than a journal + compaction scheme. A restarted
  // master preloads the file, so liveness/metadata survive rank-0 death.
  std::string snapshot_path;

  // Snapshot I/O must NOT run under `mu`: an fsync there blocks every
  // concurrent get/wait behind disk latency (heartbeat-heavy elastic
  // jobs make that visible). Mutators serialize the map to a memory
  // buffer under `mu` (cheap memcpy) and write the file under a
  // dedicated `persist_mu` after releasing `mu`; `persist_mu` keeps
  // whole snapshots ordered so a slow writer can't interleave with a
  // later one.
  std::mutex persist_mu;
  uint64_t snap_seq = 0;              // stamped under mu
  uint64_t last_persisted_seq = 0;    // guarded by persist_mu

  // Format: u64 count, then per entry u32 klen, key, u64 vlen, val.
  std::string serialize_locked() const {
    std::string buf;
    uint64_t n = kv.size();
    buf.append(reinterpret_cast<const char*>(&n), 8);
    for (const auto& it : kv) {
      uint32_t klen = static_cast<uint32_t>(it.first.size());
      uint64_t vlen = it.second.size();
      buf.append(reinterpret_cast<const char*>(&klen), 4);
      buf.append(it.first.data(), klen);
      buf.append(reinterpret_cast<const char*>(&vlen), 8);
      if (vlen) buf.append(it.second.data(), vlen);
    }
    return buf;
  }

  void persist_buffer(uint64_t seq, const std::string& buf) {
    if (snapshot_path.empty()) return;
    std::lock_guard<std::mutex> pg(persist_mu);
    // a later mutation's snapshot may have won the race for persist_mu
    // already; writing this OLDER one over it would resurrect stale
    // state after an acked newer write (lost durability) — skip it
    if (seq <= last_persisted_seq) return;
    std::string tmp = snapshot_path + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) return;
    bool ok = std::fwrite(buf.data(), 1, buf.size(), f) == buf.size();
    if (ok && std::fflush(f) != 0) ok = false;
    if (ok) ok = ::fsync(fileno(f)) == 0;
    if (std::fclose(f) != 0) ok = false;
    // only replace the last good snapshot with a fully written one —
    // a short write (ENOSPC, I/O error) must not destroy prior state
    if (ok) {
      std::rename(tmp.c_str(), snapshot_path.c_str());
      last_persisted_seq = seq;
    } else {
      std::remove(tmp.c_str());
    }
  }

  void preload() {
    if (snapshot_path.empty()) return;
    FILE* f = std::fopen(snapshot_path.c_str(), "rb");
    if (!f) return;
    uint64_t n = 0;
    if (std::fread(&n, 8, 1, f) == 1) {
      for (uint64_t i = 0; i < n; ++i) {
        uint32_t klen = 0;
        if (std::fread(&klen, 4, 1, f) != 1 || klen > (1u << 20)) break;
        std::string key(klen, '\0');
        if (klen && std::fread(key.data(), 1, klen, f) != klen) break;
        uint64_t vlen = 0;
        if (std::fread(&vlen, 8, 1, f) != 1 || vlen > kMaxValLen) break;
        std::vector<char> val(vlen);
        if (vlen && std::fread(val.data(), 1, vlen, f) != vlen) break;
        kv[std::move(key)] = std::move(val);
      }
    }
    std::fclose(f);
  }

  ~Server() { shutdown(); }

  void shutdown() {
    bool expected = false;
    if (!stop.compare_exchange_strong(expected, true)) return;
    if (listen_fd >= 0) ::shutdown(listen_fd, SHUT_RDWR), ::close(listen_fd);
    cv.notify_all();
    {
      // unblock handler threads parked in recv on live client sockets
      std::lock_guard<std::mutex> g(conns_mu);
      for (int fd : conn_fds) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread.joinable()) accept_thread.join();
    std::lock_guard<std::mutex> g(conns_mu);
    for (auto& t : conns)
      if (t.joinable()) t.join();
  }

  void handle(int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    for (;;) {
      uint8_t cmd;
      if (!recv_all(fd, &cmd, 1)) break;
      uint32_t klen = 0;
      if (!recv_all(fd, &klen, 4) || klen > (1u << 20)) break;
      std::string key(klen, '\0');
      if (klen && !recv_all(fd, key.data(), klen)) break;

      if (cmd == kSet) {
        uint64_t vlen = 0;
        if (!recv_all(fd, &vlen, 8) || vlen > kMaxValLen) break;
        std::vector<char> val(vlen);
        if (vlen && !recv_all(fd, val.data(), vlen)) break;
        std::string snap;
        uint64_t seq = 0;
        {
          std::lock_guard<std::mutex> g(mu);
          kv[key] = std::move(val);
          if (!snapshot_path.empty()) {
            snap = serialize_locked();
            seq = ++snap_seq;
          }
        }
        cv.notify_all();
        if (!snap.empty()) persist_buffer(seq, snap);
        uint8_t st = kOk;
        if (!send_all(fd, &st, 1)) break;
      } else if (cmd == kGet || cmd == kWait || cmd == kTryGet) {
        int64_t timeout_ms = 0;
        if (!recv_all(fd, &timeout_ms, 8)) break;
        std::unique_lock<std::mutex> lk(mu);
        auto ready = [&] { return stop.load() || kv.count(key) > 0; };
        bool ok;
        if (cmd == kTryGet) {
          ok = kv.count(key) > 0;
        } else if (timeout_ms <= 0) {
          cv.wait(lk, ready);
          ok = kv.count(key) > 0;
        } else {
          ok = cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                           ready) &&
               kv.count(key) > 0;
        }
        if (!ok) {
          lk.unlock();
          uint8_t st = (cmd == kTryGet) ? kMissing : kTimeout;
          if (!send_all(fd, &st, 1)) break;
          continue;
        }
        std::vector<char> val = kv[key];
        lk.unlock();
        uint8_t st = kOk;
        uint64_t vlen = (cmd == kWait) ? 0 : val.size();
        if (!send_all(fd, &st, 1)) break;
        if (cmd != kWait) {
          if (!send_all(fd, &vlen, 8)) break;
          if (vlen && !send_all(fd, val.data(), vlen)) break;
        }
      } else if (cmd == kAdd) {
        int64_t delta = 0;
        if (!recv_all(fd, &delta, 8)) break;
        int64_t result;
        std::string snap_add;
        uint64_t seq_add = 0;
        {
          std::lock_guard<std::mutex> g(mu);
          int64_t cur = 0;
          auto it = kv.find(key);
          if (it != kv.end() && it->second.size() == 8)
            memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::vector<char> v(8);
          memcpy(v.data(), &cur, 8);
          kv[key] = std::move(v);
          if (!snapshot_path.empty()) {
            snap_add = serialize_locked();
            seq_add = ++snap_seq;
          }
          result = cur;
        }
        cv.notify_all();
        if (!snap_add.empty()) persist_buffer(seq_add, snap_add);
        uint8_t st = kOk;
        if (!send_all(fd, &st, 1) || !send_all(fd, &result, 8)) break;
      } else if (cmd == kDelete) {
        size_t n;
        std::string snap_del;
        uint64_t seq_del = 0;
        {
          std::lock_guard<std::mutex> g(mu);
          n = kv.erase(key);
          if (n && !snapshot_path.empty()) {
            snap_del = serialize_locked();
            seq_del = ++snap_seq;
          }
        }
        if (!snap_del.empty()) persist_buffer(seq_del, snap_del);
        uint8_t st = n ? kOk : kMissing;
        if (!send_all(fd, &st, 1)) break;
      } else if (cmd == kNumKeys) {
        int64_t n;
        {
          std::lock_guard<std::mutex> g(mu);
          n = static_cast<int64_t>(kv.size());
        }
        uint8_t st = kOk;
        if (!send_all(fd, &st, 1) || !send_all(fd, &n, 8)) break;
      } else {
        break;
      }
    }
    ::close(fd);
  }

  void accept_loop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stop.load()) return;
        continue;
      }
      std::lock_guard<std::mutex> g(conns_mu);
      conn_fds.push_back(fd);
      conns.emplace_back([this, fd] { handle(fd); });
    }
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;  // one request in flight per client
};


}  // namespace

extern "C" {

// Returns the bound port (>0) on success (port=0 picks a free one),
// negative errno on failure. *out_handle receives the server. host
// limits the listening interface (the store is unauthenticated —
// binding INADDR_ANY would let any network peer write keys / push
// large values at rank 0); null/empty falls back to all interfaces
// for multi-host rendezvous.
int64_t tcps_server_start_persist(const char* host, int port,
                                  const char* snapshot_path,
                                  void** out_handle);

int64_t tcps_server_start_host(const char* host, int port,
                               void** out_handle) {
  return tcps_server_start_persist(host, port, nullptr, out_handle);
}

// snapshot_path (nullable): persist the map across master restarts —
// a new server started with the same path preloads the saved state
// (the etcd-backed elastic master's durability, without etcd).
int64_t tcps_server_start_persist(const char* host, int port,
                                  const char* snapshot_path,
                                  void** out_handle) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  if (host && host[0] &&
      ::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    // not a literal IP: resolve (e.g. "localhost", pod DNS names)
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (getaddrinfo(host, nullptr, &hints, &res) == 0 && res) {
      addr.sin_addr = reinterpret_cast<sockaddr_in*>(
          res->ai_addr)->sin_addr;
      freeaddrinfo(res);
    } else {
      ::close(fd);
      return -EINVAL;
    }
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* s = new Server();
  s->listen_fd = fd;
  if (snapshot_path && snapshot_path[0]) {
    s->snapshot_path = snapshot_path;
    s->preload();
  }
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  *out_handle = s;
  return ntohs(addr.sin_port);
}

// back-compat: bind all interfaces
int64_t tcps_server_start(int port, void** out_handle) {
  return tcps_server_start_host(nullptr, port, out_handle);
}

void tcps_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  delete s;  // ~Server joins everything
}

void* tcps_connect(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return nullptr;
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms > 0 ? timeout_ms
                                                           : 30000);
  while (::connect(fd, reinterpret_cast<sockaddr*>(&addr),
                   sizeof(addr)) < 0) {
    ::close(fd);
    if (std::chrono::steady_clock::now() > deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client();
  c->fd = fd;
  return c;
}

void tcps_close(void* h) {
  // Shut down under the request mutex, and do NOT free: another thread
  // (e.g. a heartbeat daemon) may be blocked inside an RPC on this
  // client — freeing here is a use-after-free/SIGSEGV. The in-flight
  // RPC fails cleanly on the closed fd; the small struct is leaked
  // intentionally (bounded by the number of stores a process closes).
  auto* c = static_cast<Client*>(h);
  if (c->fd >= 0) ::shutdown(c->fd, SHUT_RDWR);  // unblock in-flight RPC
  std::lock_guard<std::mutex> g(c->mu);
  if (c->fd >= 0) {
    ::close(c->fd);
    c->fd = -1;
  }
}

static bool send_req_header(Client* c, uint8_t cmd, const char* key) {
  uint32_t klen = static_cast<uint32_t>(strlen(key));
  return send_all(c->fd, &cmd, 1) && send_all(c->fd, &klen, 4) &&
         send_all(c->fd, key, klen);
}

int tcps_set(void* h, const char* key, const void* val, uint64_t len) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  if (!send_req_header(c, kSet, key) || !send_all(c->fd, &len, 8) ||
      (len && !send_all(c->fd, val, len)))
    return -1;
  uint8_t st;
  return recv_all(c->fd, &st, 1) && st == kOk ? 0 : -1;
}

// Returns value length (copied into out up to cap), -1 error,
// -2 timeout, -3 missing (try_get only).
int64_t tcps_get_impl(Client* c, uint8_t cmd, const char* key, void* out,
                      uint64_t cap, int64_t timeout_ms) {
  std::lock_guard<std::mutex> g(c->mu);
  if (!send_req_header(c, cmd, key) ||
      !send_all(c->fd, &timeout_ms, 8))
    return -1;
  uint8_t st;
  if (!recv_all(c->fd, &st, 1)) return -1;
  if (st == kTimeout) return -2;
  if (st == kMissing) return -3;
  if (st != kOk) return -1;
  if (cmd == kWait) return 0;
  uint64_t vlen;
  if (!recv_all(c->fd, &vlen, 8)) return -1;
  std::vector<char> val(vlen);
  if (vlen && !recv_all(c->fd, val.data(), vlen)) return -1;
  if (out && cap) memcpy(out, val.data(), std::min(cap, vlen));
  return static_cast<int64_t>(vlen);
}

int64_t tcps_get(void* h, const char* key, void* out, uint64_t cap,
                 int64_t timeout_ms) {
  return tcps_get_impl(static_cast<Client*>(h), kGet, key, out, cap,
                       timeout_ms);
}

int64_t tcps_try_get(void* h, const char* key, void* out, uint64_t cap) {
  return tcps_get_impl(static_cast<Client*>(h), kTryGet, key, out, cap, 0);
}

int tcps_wait(void* h, const char* key, int64_t timeout_ms) {
  int64_t r = tcps_get_impl(static_cast<Client*>(h), kWait, key, nullptr,
                            0, timeout_ms);
  return r >= 0 ? 0 : static_cast<int>(r);
}

int64_t tcps_add(void* h, const char* key, int64_t delta) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  if (!send_req_header(c, kAdd, key) || !send_all(c->fd, &delta, 8))
    return INT64_MIN;
  uint8_t st;
  int64_t result;
  if (!recv_all(c->fd, &st, 1) || st != kOk ||
      !recv_all(c->fd, &result, 8))
    return INT64_MIN;
  return result;
}

int tcps_delete(void* h, const char* key) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  if (!send_req_header(c, kDelete, key)) return -1;
  uint8_t st;
  if (!recv_all(c->fd, &st, 1)) return -1;
  return st == kOk ? 0 : (st == kMissing ? -3 : -1);
}

int64_t tcps_num_keys(void* h) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  if (!send_req_header(c, kNumKeys, "")) return -1;
  uint8_t st;
  int64_t n;
  if (!recv_all(c->fd, &st, 1) || st != kOk || !recv_all(c->fd, &n, 8))
    return -1;
  return n;
}

}  // extern "C"
