// Shared-memory message channel for DataLoader worker → trainer tensor
// transport.
//
// TPU-native counterpart of the reference's mmap tensor transport
// (paddle/fluid/memory/allocation/mmap_allocator.cc + the dataloader
// worker shm path): a POSIX shm ring buffer with a process-shared
// mutex/condvar pair, carrying length-prefixed pickled batches. One
// channel per worker (SPSC); blocking push/pop with timeouts.

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <new>
#include <string>

namespace {

struct RingHeader {
  pthread_mutex_t mu;
  pthread_cond_t nonempty;
  pthread_cond_t nonfull;
  uint64_t capacity;  // data bytes
  uint64_t head;      // read offset (monotonic, mod capacity)
  uint64_t tail;      // write offset (monotonic, mod capacity)
  uint32_t closed;
  uint32_t magic;
};

constexpr uint32_t kMagic = 0x53484d43;  // "SHMC"

struct Channel {
  RingHeader* hdr = nullptr;
  char* data = nullptr;
  size_t total = 0;
  std::string name;
  bool owner = false;
};

uint64_t used(const RingHeader* h) { return h->tail - h->head; }

void copy_in(Channel* ch, uint64_t pos, const void* src, uint64_t n) {
  uint64_t off = pos % ch->hdr->capacity;
  uint64_t first = std::min(n, ch->hdr->capacity - off);
  memcpy(ch->data + off, src, first);
  if (n > first)
    memcpy(ch->data, static_cast<const char*>(src) + first, n - first);
}

void copy_out(Channel* ch, uint64_t pos, void* dst, uint64_t n) {
  uint64_t off = pos % ch->hdr->capacity;
  uint64_t first = std::min(n, ch->hdr->capacity - off);
  memcpy(dst, ch->data + off, first);
  if (n > first)
    memcpy(static_cast<char*>(dst) + first, ch->data, n - first);
}

bool abs_deadline(timespec* ts, int64_t timeout_ms) {
  if (timeout_ms <= 0) return false;
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
  return true;
}

}  // namespace

extern "C" {

// capacity: ring data size in bytes. Returns handle or nullptr.
void* shmch_create(const char* name, uint64_t capacity) {
  size_t total = sizeof(RingHeader) + capacity;
  shm_unlink(name);  // stale ring from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = static_cast<RingHeader*>(mem);
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->nonempty, &ca);
  pthread_cond_init(&hdr->nonfull, &ca);
  hdr->capacity = capacity;
  hdr->head = hdr->tail = 0;
  hdr->closed = 0;
  hdr->magic = kMagic;
  auto* ch = new Channel();
  ch->hdr = hdr;
  ch->data = static_cast<char*>(mem) + sizeof(RingHeader);
  ch->total = total;
  ch->name = name;
  ch->owner = true;
  return ch;
}

void* shmch_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 ||
      st.st_size < static_cast<off_t>(sizeof(RingHeader))) {
    ::close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<RingHeader*>(mem);
  if (hdr->magic != kMagic) {
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  auto* ch = new Channel();
  ch->hdr = hdr;
  ch->data = static_cast<char*>(mem) + sizeof(RingHeader);
  ch->total = static_cast<size_t>(st.st_size);
  ch->name = name;
  return ch;
}

static int lock_robust(RingHeader* h) {
  int r = pthread_mutex_lock(&h->mu);
  if (r == EOWNERDEAD) {  // peer died holding the lock
    pthread_mutex_consistent(&h->mu);
    return 0;
  }
  return r;
}

// 0 ok, -2 timeout, -4 closed, -5 message larger than ring, -1 error.
int shmch_push(void* handle, const void* buf, uint64_t len,
               int64_t timeout_ms) {
  auto* ch = static_cast<Channel*>(handle);
  RingHeader* h = ch->hdr;
  uint64_t need = len + 8;
  if (need > h->capacity) return -5;
  timespec ts;
  bool timed = abs_deadline(&ts, timeout_ms);
  if (lock_robust(h) != 0) return -1;
  while (h->capacity - used(h) < need && !h->closed) {
    int r = timed ? pthread_cond_timedwait(&h->nonfull, &h->mu, &ts)
                  : pthread_cond_wait(&h->nonfull, &h->mu);
    if (r == EOWNERDEAD) {  // peer died holding mu during the wait
      pthread_mutex_consistent(&h->mu);
      continue;
    }
    if (r == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -4;
  }
  copy_in(ch, h->tail, &len, 8);
  copy_in(ch, h->tail + 8, buf, len);
  h->tail += need;
  pthread_cond_signal(&h->nonempty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Returns message length and copies up to cap bytes into out;
// -2 timeout, -4 closed-and-drained, -1 error. cap < len drops the
// tail (callers size via shmch_peek_len first).
int64_t shmch_pop(void* handle, void* out, uint64_t cap,
                  int64_t timeout_ms) {
  auto* ch = static_cast<Channel*>(handle);
  RingHeader* h = ch->hdr;
  timespec ts;
  bool timed = abs_deadline(&ts, timeout_ms);
  if (lock_robust(h) != 0) return -1;
  while (used(h) == 0 && !h->closed) {
    int r = timed ? pthread_cond_timedwait(&h->nonempty, &h->mu, &ts)
                  : pthread_cond_wait(&h->nonempty, &h->mu);
    if (r == EOWNERDEAD) {  // peer died holding mu during the wait
      pthread_mutex_consistent(&h->mu);
      continue;
    }
    if (r == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
  }
  if (used(h) == 0 && h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -4;
  }
  uint64_t len;
  copy_out(ch, h->head, &len, 8);
  copy_out(ch, h->head + 8, out, std::min(cap, len));
  h->head += len + 8;
  pthread_cond_signal(&h->nonfull);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len);
}

// Length of the next message without consuming it; -2 timeout, -4 closed.
int64_t shmch_peek_len(void* handle, int64_t timeout_ms) {
  auto* ch = static_cast<Channel*>(handle);
  RingHeader* h = ch->hdr;
  timespec ts;
  bool timed = abs_deadline(&ts, timeout_ms);
  if (lock_robust(h) != 0) return -1;
  while (used(h) == 0 && !h->closed) {
    int r = timed ? pthread_cond_timedwait(&h->nonempty, &h->mu, &ts)
                  : pthread_cond_wait(&h->nonempty, &h->mu);
    if (r == EOWNERDEAD) {  // peer died holding mu during the wait
      pthread_mutex_consistent(&h->mu);
      continue;
    }
    if (r == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
  }
  if (used(h) == 0 && h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -4;
  }
  uint64_t len;
  copy_out(ch, h->head, &len, 8);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len);
}

void shmch_close_write(void* handle) {  // producer EOF
  auto* ch = static_cast<Channel*>(handle);
  if (lock_robust(ch->hdr) == 0) {
    ch->hdr->closed = 1;
    pthread_cond_broadcast(&ch->hdr->nonempty);
    pthread_cond_broadcast(&ch->hdr->nonfull);
    pthread_mutex_unlock(&ch->hdr->mu);
  }
}

void shmch_free(void* handle) {
  auto* ch = static_cast<Channel*>(handle);
  if (ch->hdr) munmap(ch->hdr, ch->total);
  if (ch->owner) shm_unlink(ch->name.c_str());
  delete ch;
}

}  // extern "C"
