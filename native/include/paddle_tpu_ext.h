// Custom-op extension header (the reference's paddle/extension.h
// counterpart, reduced to a C ABI so ctypes can load user libraries
// without pybind11).
//
// A user op is a C function over PTE_Tensor views:
//
//   #include "paddle_tpu_ext.h"
//   static void relu_fwd(const PTE_Tensor* in, int n_in,
//                        PTE_Tensor* out, int n_out) {
//     const float* x = (const float*)in[0].data;
//     float* y = (float*)out[0].data;
//     for (int64_t i = 0; i < pte_numel(&in[0]); ++i)
//       y[i] = x[i] > 0 ? x[i] : 0;
//   }
//   PTE_REGISTER_OP(custom_relu, relu_fwd, 1);
//
// Outputs are pre-allocated by the framework from the op's Python-side
// shape inference (default: same shape/dtype as input 0).

#ifndef PADDLE_TPU_EXT_H_
#define PADDLE_TPU_EXT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

// dtype codes match numpy kind ordering used by the Python bridge
enum PTE_DType {
  PTE_FLOAT32 = 0,
  PTE_FLOAT64 = 1,
  PTE_INT32 = 2,
  PTE_INT64 = 3,
  PTE_BOOL = 4,
  PTE_UINT8 = 5,
  PTE_INT8 = 6,
  PTE_FLOAT16 = 7,
  PTE_BFLOAT16 = 8,
};

typedef struct {
  void* data;
  const int64_t* shape;
  int32_t ndim;
  int32_t dtype;  // PTE_DType
} PTE_Tensor;

static inline int64_t pte_numel(const PTE_Tensor* t) {
  int64_t n = 1;
  for (int32_t i = 0; i < t->ndim; ++i) n *= t->shape[i];
  return n;
}

typedef void (*pte_kernel_fn)(const PTE_Tensor* inputs, int n_inputs,
                              PTE_Tensor* outputs, int n_outputs);

// --- registry (one per user library) ------------------------------------
#define PTE_MAX_OPS 256

typedef struct {
  const char* name;
  pte_kernel_fn fn;
  int n_outputs;
} PTE_OpEntry;

// defined once per shared library by PTE_DEFINE_REGISTRY (emitted
// automatically below)
extern PTE_OpEntry pte_registry[PTE_MAX_OPS];
extern int pte_registry_size;

#ifdef __cplusplus
}
#endif

// Registry storage + accessors, emitted exactly once per user library.
#ifndef PTE_NO_DEFINE_REGISTRY
#ifdef __cplusplus
extern "C" {
#endif
PTE_OpEntry pte_registry[PTE_MAX_OPS];
int pte_registry_size = 0;

int pte_num_ops(void) { return pte_registry_size; }
const char* pte_op_name(int i) { return pte_registry[i].name; }
int pte_op_n_outputs(int i) { return pte_registry[i].n_outputs; }
void pte_op_call(int i, const PTE_Tensor* inputs, int n_inputs,
                 PTE_Tensor* outputs, int n_outputs) {
  pte_registry[i].fn(inputs, n_inputs, outputs, n_outputs);
}
#ifdef __cplusplus
}
#endif
#endif  // PTE_NO_DEFINE_REGISTRY

// Registration: a constructor-attributed function appends to the
// registry before main/dlopen returns.
#define PTE_REGISTER_OP(op_name, kernel, n_out)                        \
  __attribute__((constructor)) static void pte_reg_##op_name(void) {   \
    if (pte_registry_size < PTE_MAX_OPS) {                             \
      pte_registry[pte_registry_size].name = #op_name;                 \
      pte_registry[pte_registry_size].fn = (kernel);                   \
      pte_registry[pte_registry_size].n_outputs = (n_out);             \
      pte_registry_size++;                                             \
    }                                                                  \
  }

#endif  // PADDLE_TPU_EXT_H_
